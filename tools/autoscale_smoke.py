"""Autoscale-controller smoke gate for tools/ci_check.sh.

Runs the bench harness's autoscale measurement
(client_tpu.perf.bench_child.run_autoscale_measure) against an
in-process core: a 10x diurnal load swing (chaos OverloadScenario
trace mode, low -> 10x -> low) against a controller-governed model
(min 1 / max 4 replicas), with one serving replica chaos-killed
mid-swing. Gates on the ISSUE-17 acceptance criteria:

* priority-1 foreground p99 stays within the model's configured SLO
  through the whole swing (the controller grew capacity in time),
* replica-seconds consumed <= 0.6x of a max-scale-always fleet over
  the same window (the controller shrank capacity in time),
* >= 1 scale-up AND >= 1 scale-down decision fired, each with a
  flight-recorded decision record (the post-incident audit trail),
* the mid-swing replica kill is fully masked: 0 foreground errors
  while one fault domain was hard-failed.

The p99 and replica-seconds gates measure wall-clock behavior on a
shared, throttled CI box, so one retry is allowed; the correctness
gates (scale events, flight records, kill masking) must hold on every
attempt.

Usage: JAX_PLATFORMS=cpu python tools/autoscale_smoke.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

REPLICA_SECONDS_GATE = 0.6


def run_once(attempt: int) -> tuple:
    from client_tpu.perf.bench_child import run_autoscale_measure
    from client_tpu.server.app import build_core

    core = build_core([], warmup=False)
    try:
        result = run_autoscale_measure(
            core, model_name="autoscale_smoke_%d_" % attempt)
    finally:
        core.shutdown()
    print(json.dumps(result, indent=1))

    hard, soft = [], []
    if result.get("scale_ups", 0) < 1:
        hard.append("no scale-up decision fired under a 10x swing")
    if result.get("scale_downs", 0) < 1:
        hard.append("no scale-down decision fired after the swing")
    if result.get("flight_up_decisions", 0) < 1:
        hard.append("no flight-recorded scale-up decision — the "
                    "audit trail is missing a direction")
    if result.get("flight_down_decisions", 0) < 1:
        hard.append("no flight-recorded scale-down decision — the "
                    "audit trail is missing a direction")
    if not result.get("kill_fired"):
        hard.append("the mid-swing replica kill never fired (fleet "
                    "never reached 2 replicas during the high stage)")
    elif result.get("kill_fg_errors", 1) != 0:
        hard.append("%d foreground error(s) while one replica was "
                    "hard-killed mid-swing (want 0: redispatch + "
                    "ejection must mask the fault)"
                    % result.get("kill_fg_errors"))
    if result.get("fg_errors", 1) != 0:
        hard.append("%d foreground error(s) across the whole swing "
                    "(priority 1 must always be admitted)"
                    % result.get("fg_errors"))
    p99 = result.get("fg_p99_us", 0.0)
    slo = result.get("slo_p99_us", 0)
    if p99 > slo:
        soft.append("foreground p99 %.0f us exceeds the configured "
                    "SLO %d us (the controller did not grow in time)"
                    % (p99, slo))
    ratio = result.get("replica_seconds_ratio", 1.0)
    if ratio > REPLICA_SECONDS_GATE:
        soft.append("replica-seconds ratio %.3f exceeds %.1fx of "
                    "max-scale-always (the controller did not shrink "
                    "in time)" % (ratio, REPLICA_SECONDS_GATE))
    return result, hard, soft


def main() -> int:
    for attempt in range(2):
        result, hard, soft = run_once(attempt)
        for failure in hard:
            print("FAIL: %s" % failure, file=sys.stderr)
        if hard:
            return 1
        if not soft:
            print("autoscale smoke passed: peak %d replicas under a "
                  "10x swing, p99 %.0f us within SLO %d us, "
                  "replica-seconds %.3fx of max-scale-always "
                  "(gate %.1fx), %d up / %d down decision(s) all "
                  "flight-recorded, mid-swing kill masked"
                  % (result.get("peak_replicas", 0),
                     result.get("fg_p99_us", 0.0),
                     result.get("slo_p99_us", 0),
                     result.get("replica_seconds_ratio", 0.0),
                     REPLICA_SECONDS_GATE,
                     result.get("scale_ups", 0),
                     result.get("scale_downs", 0)))
            return 0
        for failure in soft:
            print("attempt %d: %s" % (attempt, failure), file=sys.stderr)
    print("FAIL: %s" % soft[0], file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
