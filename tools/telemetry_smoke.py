#!/usr/bin/env python
"""CI smoke for the latency-histogram / streaming-telemetry layer.

Three gates (tools/ci_check.sh step "telemetry smoke"):

1. **Presence + lint.** After a loaded unary run and a streaming run,
   /metrics must expose the histogram families
   (tpu_request_duration_us, tpu_stage_duration_us,
   tpu_stream_first_response_us, tpu_stream_inter_response_us) and
   the whole exposition must pass tools/metrics_lint.py — bucket
   ladders strictly increasing and ending +Inf, _count == +Inf
   bucket, exemplar syntax valid.
2. **Quantile fidelity.** The server p99 estimated from the
   request-duration bucket deltas of the loaded window must land
   within 2x of the client-observed p99 of the same requests — the
   bucket ladder is coarse by design (1-2-5), but a histogram whose
   p99 is off by more than the ladder step is not an SLO signal.
3. **Overhead.** The always-on recording must cost <2% throughput vs
   telemetry disabled (interleaved A/B medians on add_sub_large via
   client_tpu.perf.bench_child.run_telemetry_measure) — an SLO signal
   that must be turned off under load is not always-on.
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _simple_request(seed: int):
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    a = np.full((16,), seed % 97, dtype=np.int32)
    b = np.arange(16, dtype=np.int32)
    t0 = InferInput("INPUT0", [16], "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", [16], "INT32")
    t1.set_data_from_numpy(b)
    return get_inference_request(model_name="simple",
                                 inputs=[t0, t1], outputs=None)


def _loaded_run(core, n: int = 60, threads: int = 4):
    """Concurrent closed loop on `simple`; returns sorted client
    latencies (us)."""
    latencies: list = []
    merge = threading.Lock()

    def worker(offset: int):
        local = []
        for i in range(n):
            request = _simple_request(offset * 1000 + i)
            start = time.monotonic_ns()
            core.infer(request)
            local.append((time.monotonic_ns() - start) / 1000.0)
        with merge:
            latencies.extend(local)

    pool = [threading.Thread(target=worker, args=(i,))
            for i in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    latencies.sort()
    return latencies


def _stream_run(core, n: int = 10):
    import numpy as np

    from client_tpu.grpc._utils import get_inference_request

    for i in range(n):
        request = get_inference_request(
            model_name="repeat_int32", inputs=[], outputs=None)
        tensor = request.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "INT32"
        tensor.shape.extend([4])
        request.raw_input_contents.append(
            np.arange(i, i + 4, dtype=np.int32).tobytes())
        for _ in core.stream_infer(request):
            pass


def main() -> int:
    from metrics_lint import lint_exposition

    from client_tpu.perf.bench_child import run_telemetry_measure
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )
    from client_tpu.server.app import build_core

    failures = []
    core = build_core(["simple", "repeat_int32"])
    try:
        # Warm (compile + first-request effects outside the window).
        _loaded_run(core, n=5, threads=2)
        before_text = core.metrics_text()
        client_latencies = _loaded_run(core)
        _stream_run(core)
        after_text = core.metrics_text()

        # Gate 1: presence + lint-clean exposition.
        errors, types, _series = lint_exposition(after_text)
        for family in ("tpu_request_duration_us",
                       "tpu_stage_duration_us",
                       "tpu_stream_first_response_us",
                       "tpu_stream_inter_response_us"):
            if types.get(family) != "histogram":
                failures.append("histogram family %s missing" % family)
        if errors:
            failures.extend("lint: %s" % e for e in errors[:10])
        print("exposition: %d families, lint %s"
              % (len(types), "clean" if not errors
                 else "%d violations" % len(errors)))

        # Gate 2: bucket-estimated p99 within 2x of client p99 over
        # the same window.
        snapshots = [parse_prometheus(before_text),
                     parse_prometheus(after_text)]
        quantiles = histogram_quantiles(summarize_metrics(snapshots))
        entry = quantiles.get("request_duration_us|simple")
        if not entry:
            failures.append("no request-duration window delta for "
                            "'simple'")
        else:
            client_p99 = client_latencies[
                int(len(client_latencies) * 0.99) - 1]
            server_p99 = entry["p99_us"]
            ratio = (server_p99 / client_p99 if client_p99 > 0
                     else float("inf"))
            print("p99: server (bucket estimate) %.0f us vs client "
                  "%.0f us (%.2fx) over %d server obs"
                  % (server_p99, client_p99, ratio, entry["count"]))
            if not (0.5 <= ratio <= 2.0):
                failures.append(
                    "server bucket p99 %.0f us is not within 2x of "
                    "client p99 %.0f us" % (server_p99, client_p99))
        ttft = quantiles.get("stream_first_response_us|repeat_int32")
        itl = quantiles.get("stream_inter_response_us|repeat_int32")
        if not ttft or not itl:
            failures.append("stream TTFT/ITL window deltas missing "
                            "for repeat_int32")
        else:
            print("stream: TTFT p50 %.0f us, ITL p50 %.0f us over "
                  "%d gaps" % (ttft["p50_us"], itl["p50_us"],
                               itl["count"]))

        # Gate 3: <2% recording overhead, A/B on add_sub_large. The
        # true cost is ~microseconds against a ~15 ms request, far
        # below host noise — one retry with more interleaved pairs
        # filters transient contention (another process's burst can
        # skew a 4-pair median past 2% when the real cost is ~0).
        core.repository.load("add_sub_large")
        overhead = run_telemetry_measure(core, requests=96)
        if not overhead["overhead_ok"]:
            print("overhead first pass %.2f%% over the gate; "
                  "re-measuring with more pairs"
                  % overhead["overhead_pct"])
            overhead = run_telemetry_measure(core, requests=96,
                                             rounds=12)
        print("overhead: %.2f%% (off %.1f/s vs on %.1f/s; pairs %s; "
              "gate <%.0f%%)"
              % (overhead["overhead_pct"],
                 overhead["telemetry_off_tput"],
                 overhead["telemetry_on_tput"],
                 overhead["pair_overheads_pct"],
                 overhead["overhead_gate_pct"]))
        if not overhead["overhead_ok"]:
            failures.append("telemetry overhead %.2f%% exceeds the "
                            "2%% gate" % overhead["overhead_pct"])
    finally:
        core.shutdown()
    if failures:
        for failure in failures:
            print("telemetry smoke: %s" % failure, file=sys.stderr)
        print("telemetry smoke FAILED (%d gate violation%s)"
              % (len(failures), "s" if len(failures) != 1 else ""),
              file=sys.stderr)
        return 1
    print("telemetry smoke passed: histograms present + lint-clean, "
          "bucket p99 within 2x of client, overhead under 2%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
