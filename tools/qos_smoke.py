"""Multi-tenant QoS overload smoke gate for tools/ci_check.sh.

Runs the bench harness's overload measurement
(client_tpu.perf.bench_child.run_qos_measure) against an in-process
core: a paced priority-2 bulk burst (tenant "bulk") saturates a
bounded queue while a priority-1 foreground keeps a closed loop
running. Gates on the ISSUE-7 acceptance criteria:

* priority-1 goodput is 100% through saturation (every drop landed on
  bulk via displacement/watermark shedding, never on priority 1),
* priority-1 p99 stays within 2x its unloaded baseline,
* bulk actually saturated (server sheds/rejects observed — otherwise
  the run proved nothing), and
* mixed-priority fusion parity: the c16 mixed run's fusion ratio is
  within 10% of the single-class run's (QoS ordering costs dispatch
  order, not batch efficiency).

The latency gate involves OS scheduling at ms scale, so one retry is
allowed; the correctness gates (goodput, sheds, fusion) must hold on
every attempt.

Usage: JAX_PLATFORMS=cpu python tools/qos_smoke.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def run_once(attempt: int) -> tuple:
    from client_tpu.server.app import build_core
    from client_tpu.perf.bench_child import run_qos_measure

    core = build_core([], warmup=False)
    try:
        result = run_qos_measure(core, model_name="qos_smoke_%d" % attempt)
    finally:
        core.shutdown()
    print(json.dumps(result, indent=1))

    hard, soft = [], []
    if result.get("p1_goodput_pct") != 100.0:
        hard.append("priority-1 goodput %.2f%% under saturation "
                    "(want 100%%)" % result.get("p1_goodput_pct", 0.0))
    dropped = (result.get("bulk_server_sheds", 0)
               + result.get("bulk_server_rejects", 0))
    if dropped <= 0:
        hard.append("bulk burst never saturated the queue (0 server "
                    "sheds/rejects) — the run proved nothing")
    parity = result.get("fusion_mixed_vs_single", 0.0)
    if not 0.9 <= parity <= 1.1:
        hard.append("mixed-priority fusion ratio is %.3fx the "
                    "single-class run (want within 10%%)" % parity)
    ratio = result.get("p1_p99_vs_unloaded", 0.0)
    if not 0 < ratio <= 2.0:
        soft.append("priority-1 p99 %.2fx its unloaded baseline "
                    "(gate: 2x)" % ratio)
    return result, hard, soft


def main() -> int:
    for attempt in range(2):
        result, hard, soft = run_once(attempt)
        for failure in hard:
            print("FAIL: %s" % failure, file=sys.stderr)
        if hard:
            return 1
        if not soft:
            print("qos smoke passed: priority-1 p99 %.2fx unloaded at "
                  "100%% goodput, %d bulk sheds at saturation, mixed "
                  "fusion parity %.3f"
                  % (result.get("p1_p99_vs_unloaded", 0.0),
                     result.get("bulk_server_sheds", 0)
                     + result.get("bulk_server_rejects", 0),
                     result.get("fusion_mixed_vs_single", 0.0)))
            return 0
        for failure in soft:
            print("attempt %d: %s" % (attempt, failure), file=sys.stderr)
    print("FAIL: %s" % soft[0], file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
