#!/usr/bin/env python
"""CI smoke for the overlapped output-fetch subsystem
(client_tpu.server.fetch; tools/ci_check.sh step "fetch smoke").

Three gates:

1. **Golden parity.** The ``fetch_bench`` / ``fetch_bench_legacy``
   A/B pair (identical 4-output x 4 MiB models, overlapped vs serial
   legacy fetch) must produce byte-identical responses under
   concurrent fused load — including an output landed directly in a
   registered system-shm region (fetch-into-region vs the legacy
   staged copy).

2. **No-regression on real arrays.** The server-side
   ``tpu_stage_duration_us{stage=relay_fetch}`` p50 of the overlapped
   arm must not exceed the legacy arm's. On the cpu backend both arms
   materialize committed host buffers (np.asarray is a zero-copy
   view) so the ratio sits near 1; on an accelerator this same gate
   observes the real device->host win (the bench relay_fetch stage
   records the measured ratio).

3. **Overlap property.** A simulated-DMA pair — same model, each of
   its 4 outputs costing a fixed per-output transfer latency to
   materialize — must show the overlapped arm's relay_fetch p50 at
   least 2x below the serial legacy arm's. This is the mechanism gate:
   concurrent landings genuinely overlap, independent of platform.
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _SimDeviceArray:
    """Array-like with a fixed host-materialization latency — a
    deterministic stand-in for a device->host DMA so the overlap gate
    measures scheduling, not platform copy speed."""

    def __init__(self, data, delay_s):
        self._data = data
        self._delay_s = delay_s
        self.shape = data.shape
        self.dtype = data.dtype
        self.nbytes = data.nbytes

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._delay_s)
        return self._data


def _sim_model_factory(name: str, overlapped: bool, delay_s: float):
    import numpy as np

    from client_tpu.server.model import ServedModel, TensorSpec

    class SimFetchModel(ServedModel):
        max_batch_size = 4
        dynamic_batching = True
        preferred_batch_sizes = [4]
        max_queue_delay_us = 3000

        def __init__(self):
            super().__init__()
            self.name = name
            self.overlapped_fetch = overlapped
            self.inputs = [TensorSpec("IN", "FP32", [8])]
            self.outputs = [TensorSpec("OUT%d" % i, "FP32", [8])
                            for i in range(4)]

        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"], dtype=np.float32)
            return {
                "OUT%d" % i: _SimDeviceArray(array + float(i), delay_s)
                for i in range(4)
            }

    return SimFetchModel


def _request(model: str, seed: int, elements: int):
    import numpy as np

    from client_tpu.protocol import inference_pb2 as pb

    request = pb.ModelInferRequest(model_name=model,
                                   id="%s-%d" % (model, seed))
    tensor = request.inputs.add()
    tensor.name = "INPUT0" if model.startswith("fetch_bench") else "IN"
    tensor.datatype = "FP32"
    tensor.shape.extend([1, elements])
    request.raw_input_contents.append(
        np.full((1, elements), float(seed % 31), np.float32).tobytes())
    return request


def _loaded_run(core, model: str, elements: int, n: int = 8,
                threads: int = 4):
    """Concurrent closed loop so the dynamic batcher fuses; returns
    {request_id: response} for parity checks."""
    responses = {}
    merge = threading.Lock()
    errors = []

    def worker(offset: int):
        local = {}
        for i in range(n):
            seed = offset * 100 + i
            try:
                local[seed] = core.infer(_request(model, seed, elements))
            except Exception as e:  # noqa: BLE001 — gate fails below
                errors.append(e)
                return
        with merge:
            responses.update(local)

    pool = [threading.Thread(target=worker, args=(t,))
            for t in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    if errors:
        raise errors[0]
    return responses


def _relay_p50(before: str, after: str, model: str):
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )

    summary = summarize_metrics([parse_prometheus(before),
                                 parse_prometheus(after)])
    entry = histogram_quantiles(summary).get(
        "stage_duration_us|%s|srelay_fetch" % model)
    return entry


def main() -> int:
    import numpy as np

    from client_tpu.server.app import build_core
    from client_tpu.server.core import InferenceServerCore
    from client_tpu.server.repository import ModelRepository
    from client_tpu.utils import shared_memory as system_shm

    failures = []

    # -- gates 1 + 2: the real-array A/B pair ---------------------------
    core = build_core(["fetch_bench", "fetch_bench_legacy"])
    try:
        _loaded_run(core, "fetch_bench", 16, n=2, threads=2)  # warm
        _loaded_run(core, "fetch_bench_legacy", 16, n=2, threads=2)
        before = core.metrics_text()
        # Interleaved A/B rounds: alternating windows cancel drift
        # (allocator warmth, page cache, background load) that a
        # run-A-then-run-B layout folds into the comparison.
        overlapped, legacy = {}, {}
        for _ in range(3):
            overlapped.update(
                _loaded_run(core, "fetch_bench", 16, n=3, threads=4))
            legacy.update(
                _loaded_run(core, "fetch_bench_legacy", 16, n=3,
                            threads=4))
        after = core.metrics_text()

        mismatches = 0
        for seed, response in sorted(overlapped.items()):
            baseline = legacy.get(seed)
            if baseline is None:
                continue
            if [t.name for t in response.outputs] != \
                    [t.name for t in baseline.outputs] or \
                    list(response.raw_output_contents) != \
                    list(baseline.raw_output_contents):
                mismatches += 1
        print("parity: %d requests compared, %d mismatches"
              % (len(overlapped), mismatches))
        if mismatches:
            failures.append("overlapped vs legacy responses differ "
                            "(%d mismatches)" % mismatches)

        # Shm-bound output: the region must land the same bytes the
        # wire path serializes.
        region = system_shm.create_shared_memory_region(
            "fetch_smoke_out", "/fetch_smoke_out", 4 << 20)
        core.register_system_shm("fetch_smoke_out", "/fetch_smoke_out",
                                 0, 4 << 20)
        try:
            request = _request("fetch_bench", 7, 16)
            spec = request.outputs.add(name="OUTPUT0")
            spec.parameters[
                "shared_memory_region"].string_param = "fetch_smoke_out"
            spec.parameters[
                "shared_memory_byte_size"].int64_param = 4 << 20
            rider = threading.Thread(
                target=lambda: core.infer(_request("fetch_bench", 8, 16)))
            rider.start()  # a second member so the batch fuses
            core.infer(request)
            rider.join()
            wire = core.infer(_request("fetch_bench", 7, 16))
            landed = bytes(region.buf()[:4 << 20])
            golden = next(
                raw for tensor, raw in zip(wire.outputs,
                                           wire.raw_output_contents)
                if tensor.name == "OUTPUT0")
            if landed != golden:
                first = next((i for i in range(len(golden))
                              if landed[i] != golden[i]), -1)
                failures.append(
                    "shm-landed OUTPUT0 differs from wire bytes "
                    "(first diff at %d)" % first)
            else:
                print("parity: shm-landed OUTPUT0 matches wire bytes "
                      "(%d bytes)" % len(golden))
        finally:
            core.unregister_system_shm("fetch_smoke_out")
            system_shm.destroy_shared_memory_region(region)

        over_entry = _relay_p50(before, after, "fetch_bench")
        legacy_entry = _relay_p50(before, after, "fetch_bench_legacy")
        if not over_entry or not legacy_entry:
            failures.append("relay_fetch stage histograms missing for "
                            "the fetch_bench pair")
        else:
            ratio = (over_entry["p50_us"] / legacy_entry["p50_us"]
                     if legacy_entry["p50_us"] > 0 else 0.0)
            print("real arrays: relay_fetch p50 overlapped %.0f us vs "
                  "legacy %.0f us (%.2fx) over %d/%d executions"
                  % (over_entry["p50_us"], legacy_entry["p50_us"],
                     ratio, over_entry["count"], legacy_entry["count"]))
            # Bucket-quantile estimates are ladder-coarse (1-2-5):
            # allow one bucket step of slack on the no-regression gate.
            if over_entry["p50_us"] > legacy_entry["p50_us"] * 2.5:
                failures.append(
                    "overlapped relay_fetch p50 %.0f us regressed past "
                    "legacy %.0f us" % (over_entry["p50_us"],
                                        legacy_entry["p50_us"]))
    finally:
        core.shutdown()

    # -- gate 3: simulated-DMA overlap property -------------------------
    repository = ModelRepository()
    repository.add_factory(
        "sim_fetch", _sim_model_factory("sim_fetch", True, 0.03))
    repository.add_factory(
        "sim_fetch_legacy",
        _sim_model_factory("sim_fetch_legacy", False, 0.03))
    repository.load("sim_fetch")
    repository.load("sim_fetch_legacy")
    sim_core = InferenceServerCore(repository)
    try:
        _loaded_run(sim_core, "sim_fetch", 8, n=1, threads=2)  # warm
        _loaded_run(sim_core, "sim_fetch_legacy", 8, n=1, threads=2)
        before = sim_core.metrics_text()
        sim_over = _loaded_run(sim_core, "sim_fetch", 8, n=4)
        sim_legacy = _loaded_run(sim_core, "sim_fetch_legacy", 8, n=4)
        after = sim_core.metrics_text()
        for seed, response in sorted(sim_over.items()):
            baseline = sim_legacy.get(seed)
            if baseline is not None and \
                    list(response.raw_output_contents) != \
                    list(baseline.raw_output_contents):
                failures.append("simulated pair parity mismatch")
                break
        over_entry = _relay_p50(before, after, "sim_fetch")
        legacy_entry = _relay_p50(before, after, "sim_fetch_legacy")
        if not over_entry or not legacy_entry:
            failures.append("relay_fetch stage histograms missing for "
                            "the simulated pair")
        else:
            speedup = (legacy_entry["p50_us"] / over_entry["p50_us"]
                       if over_entry["p50_us"] > 0 else float("inf"))
            print("simulated DMA: relay_fetch p50 overlapped %.0f us "
                  "vs serial %.0f us (%.1fx overlap win)"
                  % (over_entry["p50_us"], legacy_entry["p50_us"],
                     speedup))
            if speedup < 2.0:
                failures.append(
                    "overlapped fetch shows only %.1fx over serial on "
                    "4 simulated 30 ms transfers (floor: 2x)" % speedup)
    finally:
        sim_core.shutdown()

    if failures:
        for failure in failures:
            print("FAIL: %s" % failure)
        return 1
    print("fetch smoke passed: golden parity (wire + shm), "
          "no relay_fetch regression on real arrays, >=2x overlap win "
          "on simulated transfers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
