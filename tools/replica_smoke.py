"""Replica-serving chaos smoke gate for tools/ci_check.sh.

Runs the bench harness's replica measurement
(client_tpu.perf.bench_child.run_replica_measure) against an
in-process core: a delay-bound model served as 1 vs 4 per-device
replicas under an identical closed loop, then replica 2 of 4
hard-degraded mid-run via a replica-targeted DegradeOneScenario and
healed so the supervisor readmits it. Gates on the ISSUE-8 acceptance
criteria:

* client-visible goodput is 100% while one replica is hard-degraded
  (every in-flight failure re-dispatched to a healthy sibling, the
  victim ejected from routing — the blast radius is one fault domain,
  never a client error),
* at least one ejection AND one readmission are recorded (the
  self-healing supervisor actually ran: re-initialize + canary probe),
* post-recovery throughput returns to within 20% of the pre-fault
  rate, and
* data-parallel scaling: >= 2.5x throughput at 4 replicas vs 1.

The throughput-ratio gates divide two measurements on a shared,
throttled CI box, so one retry is allowed; the correctness gates
(goodput, ejection, readmission) must hold on every attempt.

Usage: JAX_PLATFORMS=cpu python tools/replica_smoke.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def run_once(attempt: int) -> tuple:
    from client_tpu.perf.bench_child import run_replica_measure
    from client_tpu.server.app import build_core

    core = build_core([], warmup=False)
    try:
        result = run_replica_measure(
            core, model_name="replica_smoke_%d_" % attempt)
    finally:
        core.shutdown()
    print(json.dumps(result, indent=1))

    hard, soft = [], []
    if result.get("degrade_goodput_pct") != 100.0:
        hard.append("goodput %.2f%% with one replica hard-degraded "
                    "(want 100%%: re-dispatch must mask the fault)"
                    % result.get("degrade_goodput_pct", 0.0))
    if result.get("ejections", 0) < 1:
        hard.append("no replica ejection recorded — the degraded "
                    "replica was never removed from routing")
    if result.get("readmissions", 0) < 1:
        hard.append("no replica readmission recorded — the supervisor "
                    "never healed the ejected replica")
    scaling = result.get("scaling_4v1", 0.0)
    if scaling < 2.5:
        soft.append("throughput at 4 replicas is %.2fx the 1-replica "
                    "rate (gate: 2.5x)" % scaling)
    recovery = result.get("recovery_vs_prefault", 0.0)
    if recovery < 0.8:
        soft.append("post-readmission throughput is %.3fx the "
                    "pre-fault rate (gate: within 20%%)" % recovery)
    return result, hard, soft


def main() -> int:
    for attempt in range(2):
        result, hard, soft = run_once(attempt)
        for failure in hard:
            print("FAIL: %s" % failure, file=sys.stderr)
        if hard:
            return 1
        if not soft:
            print("replica smoke passed: %.2fx scaling at 4 replicas, "
                  "100%% goodput through a hard-degraded replica "
                  "(%d ejection(s), %d readmission(s)), recovery "
                  "%.3fx pre-fault"
                  % (result.get("scaling_4v1", 0.0),
                     result.get("ejections", 0),
                     result.get("readmissions", 0),
                     result.get("recovery_vs_prefault", 0.0)))
            return 0
        for failure in soft:
            print("attempt %d: %s" % (attempt, failure), file=sys.stderr)
    print("FAIL: %s" % soft[0], file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
