#!/usr/bin/env python
"""Device-resident ensemble dataflow smoke (ISSUE 16 acceptance).

Runs the shared A/B driver (client_tpu.perf.bench_child.
run_ensemble_dataflow_measure): the ``ensemble_ab`` /
``ensemble_ab_legacy`` pair — identical three-step graphs whose
backbone wall cost scales with batch ROWS (ensemble-level gather
cannot amortize it), one arm executed as a device-resident dataflow
graph (per-stage batching + composing-cache short-circuit), the other
through the legacy host-mediated step loop with prod-style
ensemble-level dynamic batching.

Gates:
  1. golden parity — identical RAW inputs produce byte-identical
     SCORE bytes across arms;
  2. backbone fusion ratio (execution_count / inference_count over
     the distinct-input phase at c16) <= 0.15 — concurrent dataflow
     requests fuse in the composing model's own batcher;
  3. hot-set throughput >= 4x the legacy arm — the dataflow arm's
     stage cache short-circuits the subgraph (the retired PR-5
     composing-cache caveat, measured), the legacy arm re-pays the
     row-proportional backbone every cycle;
  4. span shape — a traced dataflow request carries per-stage
     ``ensemble_step`` spans and ZERO ``relay_fetch`` spans: interior
     tensors never detour through a host fetch.
"""

from __future__ import annotations

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SPEEDUP_FLOOR = 4.0
FUSION_CEIL = 0.15


def main() -> int:
    from client_tpu.perf.bench_child import run_ensemble_dataflow_measure

    result = run_ensemble_dataflow_measure()
    print("distinct c%d: %.1f/s p50 %.0f us; fusion %.4f "
          "(%d executions over %d backbone rows, %d fused dispatches)"
          % (result["concurrency"], result["distinct_tput"],
             result["distinct_p50_us"], result["fusion_ratio"],
             result["backbone_executions"],
             result["backbone_inferences"], result["ensemble_fused"]))
    print("hot set: dataflow %.1f/s p50 %.0f us vs legacy %.1f/s "
          "p50 %.0f us (%.2fx); %d subgraph cache hits"
          % (result["dataflow_tput"], result["dataflow_p50_us"],
             result["legacy_tput"], result["legacy_p50_us"],
             result["speedup"], result["ensemble_cache_hits"]))
    print("trace: %d ensemble_step spans, %d relay_fetch spans"
          % (result["ensemble_step_spans"],
             result["interior_relay_fetch_spans"]))

    failures = []
    if not result["golden_parity"]:
        failures.append("dataflow arm is NOT byte-identical to the "
                        "legacy host-mediated arm")
    if result["fusion_ratio"] > FUSION_CEIL:
        failures.append(
            "backbone fusion ratio %.4f above the %.2f ceiling at "
            "c%d — per-stage batching is not fusing concurrent "
            "dataflow requests" % (result["fusion_ratio"], FUSION_CEIL,
                                   result["concurrency"]))
    if result["speedup"] < SPEEDUP_FLOOR:
        failures.append(
            "hot-set throughput %.2fx below the %.1fx floor "
            "(dataflow %.1f/s vs legacy %.1f/s)"
            % (result["speedup"], SPEEDUP_FLOOR,
               result["dataflow_tput"], result["legacy_tput"]))
    if result["ensemble_cache_hits"] <= 0:
        failures.append("no subgraph cache hits on the pinned hot set")
    if result["ensemble_step_spans"] <= 0:
        failures.append("traced dataflow request carried no "
                        "ensemble_step spans")
    if result["interior_relay_fetch_spans"] != 0:
        failures.append(
            "%d relay_fetch span(s) inside the dataflow request — "
            "interior tensors detoured through a host fetch"
            % result["interior_relay_fetch_spans"])
    for failure in failures:
        print("FAIL: %s" % failure)
    if failures:
        return 1
    print("ensemble smoke passed: %.2fx hot-set throughput "
          "(floor %.1fx), fusion %.4f (ceil %.2f) at c%d, golden "
          "parity, %d ensemble_step spans with zero relay_fetch"
          % (result["speedup"], SPEEDUP_FLOOR, result["fusion_ratio"],
             FUSION_CEIL, result["concurrency"],
             result["ensemble_step_spans"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
