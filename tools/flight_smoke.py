#!/usr/bin/env python
"""CI smoke for the flight recorder + SLO burn-rate engine.

Four gates (tools/ci_check.sh step "flight smoke"), all at
``trace_rate=0`` — the whole point of tail retention is that NOTHING
was sampled at request start:

1. **Anomaly retention.** Under chaos ``latency_ms`` + ``error_rate``
   injection against ``simple_slo``, >=95% of the injected slow/error
   requests must land in the flight ring; retained slow traces must
   carry FULL span trees (root + the decode/execute/encode stages
   that tile the request).
2. **SLO burn.** ``tpu_slo_burn_rate`` for ``simple_slo`` must go >1
   during the injection (every injected request blows through the
   50 ms p99 target) ...
3. **... and recover.** After chaos is cleared and clean traffic runs
   past the fast window, the fast-window burn must fall back to <=1
   and the verdict must return to healthy.
4. **Overhead.** Always-on capture must cost <2% throughput vs
   disabled (paired interleaved A/B medians on add_sub_large via
   client_tpu.perf.bench_child.run_flight_measure — the PR-10
   methodology; a forensic layer that must be turned off under load
   is not always-on).

Also asserts the /v2/debug and /v2/debug/flight JSON stays
cardinality-bounded (tools/metrics_lint.lint_debug_snapshot).
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL = "simple_slo"
# The model's absolute flight_slow_us / slo_p99_latency_us target is
# 50 ms; the injected latency must clear it with margin.
INJECT_LATENCY_MS = 120.0
INJECT_ERROR_RATE = 0.2


def _request(seed: int):
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    a = np.full((16,), seed % 97, dtype=np.int32)
    b = np.arange(16, dtype=np.int32)
    t0 = InferInput("INPUT0", [16], "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", [16], "INT32")
    t1.set_data_from_numpy(b)
    return get_inference_request(model_name=MODEL,
                                 inputs=[t0, t1], outputs=None)


def _run_load(core, n: int, threads: int = 4) -> tuple:
    """(completed, errored) across a concurrent closed loop."""
    counts = [0, 0]
    merge = threading.Lock()
    per_thread = max(n // threads, 1)

    def worker(offset: int):
        ok = err = 0
        for i in range(per_thread):
            try:
                core.infer(_request(offset * 1000 + i))
                ok += 1
            except Exception:  # noqa: BLE001 — injected faults
                err += 1
        with merge:
            counts[0] += ok
            counts[1] += err

    pool = [threading.Thread(target=worker, args=(i,))
            for i in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    return counts[0], counts[1]


def _burn_fast(core) -> float:
    """The fast-window burn rate for MODEL from a live evaluation."""
    verdict = core.slo.evaluate(force_sample=True).get(MODEL)
    return verdict["burn"]["fast"] if verdict else 0.0


def main() -> int:
    from metrics_lint import lint_debug_snapshot, lint_exposition

    from client_tpu.perf.bench_child import run_flight_measure
    from client_tpu.server import chaos
    from client_tpu.server.app import build_core

    failures = []
    core = build_core([MODEL])
    # Tight burn windows so the smoke observes burn AND recovery in
    # seconds (production defaults are 5 m / 1 h; the math is window-
    # relative, so shrinking the windows shrinks only the wait).
    core.slo.fast_window_s = 2.0
    core.slo.slow_window_s = 6.0
    core.slo.min_sample_interval_s = 0.0
    # Ring sized above the injected-anomaly count so retention
    # measures the keep decision, not overwrite pressure.
    core.flight.max_entries = 4096
    try:
        # Tracing must be OFF: retention below is pure tail sampling.
        settings = core.trace_setting("", {})
        if (settings.get("trace_level") or ["OFF"])[0] != "OFF":
            failures.append("trace_level is not OFF at start")
        _run_load(core, n=24, threads=2)  # warm, clean baseline
        baseline_burn = _burn_fast(core)
        # Keeps before injection (e.g. the first jit-compile request
        # legitimately crossing the 5 ms threshold) are not the
        # injection's anomalies — measure retention as a delta.
        kept_before = core.flight.stats().get(MODEL, {}).get(
            "kept_total", 0)

        # -- injection window -----------------------------------------
        chaos.configure_from_spec(
            "latency_ms=%g,error_rate=%g,seed=11,models=%s"
            % (INJECT_LATENCY_MS, INJECT_ERROR_RATE, MODEL))
        ok, errored = _run_load(core, n=80)
        injected = chaos.stats()
        burn_during = _burn_fast(core)
        chaos.configure(None)

        stats = core.flight.stats().get(MODEL, {})
        kept = stats.get("kept_total", 0) - kept_before
        anomalies = ok + errored  # every injected request is slow or
        # errored: latency_ms applies to all, errors to a fraction
        retention = kept / anomalies if anomalies else 0.0
        print("retention: %d/%d injected anomalies kept (%.1f%%; "
              "%d errors, %d slow)"
              % (kept, anomalies, retention * 100.0, errored, ok))
        if retention < 0.95:
            failures.append(
                "flight ring retained %.1f%% of injected anomalies "
                "(gate >=95%%)" % (retention * 100.0))

        # Full span trees on the slow keeps (>50 ms against the
        # model's absolute threshold): root + the stage spans that
        # tile the request (decode/execute/encode at minimum).
        records = core.flight.snapshot(MODEL)
        slow = [r for r in records if r["reason"] == "slow"]
        complete = 0
        for record in slow:
            names = {span["name"] for span in record["spans"]}
            if {"request", "decode", "encode"} <= names:
                complete += 1
        print("span trees: %d/%d slow keeps complete (root + stage "
              "spans)" % (complete, len(slow)))
        if not slow:
            failures.append("no slow-kept traces in the ring")
        elif complete / len(slow) < 0.95:
            failures.append(
                "only %d/%d slow keeps carry full span trees"
                % (complete, len(slow)))

        # -- burn during injection ------------------------------------
        print("burn: baseline %.2fx, during injection %.2fx"
              % (baseline_burn, burn_during))
        if burn_during <= 1.0:
            failures.append(
                "tpu_slo_burn_rate stayed at %.2f (<=1) during "
                "injection" % burn_during)
        text = core.metrics_text()
        if "tpu_slo_burn_rate" not in text:
            failures.append("tpu_slo_burn_rate family missing from "
                            "/metrics")
        errors, _types, _series = lint_exposition(text)
        if errors:
            failures.extend("lint: %s" % e for e in errors[:5])

        # -- recovery -------------------------------------------------
        deadline = time.time() + 20.0
        burn_after = burn_during
        while time.time() < deadline:
            _run_load(core, n=16, threads=2)
            time.sleep(0.5)
            burn_after = _burn_fast(core)
            if burn_after <= 1.0:
                break
        verdict = core.slo.evaluate(force_sample=True).get(MODEL, {})
        print("recovery: burn %.2fx after clean traffic, verdict %s"
              % (burn_after,
                 "healthy" if verdict.get("healthy") else "unhealthy"))
        if burn_after > 1.0:
            failures.append(
                "fast-window burn did not recover (<=1) within 20 s "
                "of clearing chaos (still %.2f)" % burn_after)
        if not verdict.get("healthy", False):
            failures.append("verdict did not return to healthy")

        # -- debug surfaces stay bounded ------------------------------
        debug_errors = lint_debug_snapshot(core.debug_snapshot())
        debug_errors += lint_debug_snapshot(core.debug_flight(MODEL))
        if debug_errors:
            failures.extend("debug: %s" % e for e in debug_errors[:5])

        # -- capture overhead -----------------------------------------
        core.repository.load("add_sub_large")
        overhead = run_flight_measure(core, requests=96)
        if not overhead["overhead_ok"]:
            print("overhead first pass %.2f%% over the gate; "
                  "re-measuring with more pairs"
                  % overhead["overhead_pct"])
            overhead = run_flight_measure(core, requests=96, rounds=12)
        print("overhead: %.2f%% (off %.1f/s vs on %.1f/s; pairs %s; "
              "gate <%.0f%%)"
              % (overhead["overhead_pct"],
                 overhead["flight_off_tput"],
                 overhead["flight_on_tput"],
                 overhead["pair_overheads_pct"],
                 overhead["overhead_gate_pct"]))
        if not overhead["overhead_ok"]:
            failures.append("flight capture overhead %.2f%% exceeds "
                            "the 2%% gate" % overhead["overhead_pct"])
    finally:
        chaos.configure(None)
        core.shutdown()
    if failures:
        for failure in failures:
            print("flight smoke: %s" % failure, file=sys.stderr)
        print("flight smoke FAILED (%d gate violation%s)"
              % (len(failures), "s" if len(failures) != 1 else ""),
              file=sys.stderr)
        return 1
    print("flight smoke passed: >=95% anomaly retention with full "
          "span trees at trace_rate=0, burn >1 during injection and "
          "recovered after, debug surfaces bounded, capture overhead "
          "under 2%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
