#!/usr/bin/env python
"""Prometheus exposition lint for ``core.metrics_text()``.

Validates the /metrics surface the perf MetricsManager and external
scrapers consume, then proves counter monotonicity across two scrapes
taken under concurrent load (unary AND streaming, so the latency
histogram and stream-telemetry families are exercised):

* every sample's family has a ``# HELP`` and ``# TYPE`` line, and both
  appear BEFORE the family's first sample (Prometheus exposition
  format requirement);
* family/label names are legal, label values are properly escaped
  (no raw ``"``, ``\\`` or newline inside a quoted value);
* no duplicate series (family + label set appears once per scrape);
* ``_total``-suffixed families are typed ``counter``;
* histogram families are structurally sound: per label set, ``le``
  bucket bounds are unique/parseable and end in ``+Inf``, cumulative
  bucket counts are non-decreasing in ``le``, ``_count`` equals the
  ``+Inf`` bucket, and a ``_sum`` series is present;
* OpenMetrics-style exemplars (``# {trace_id="..."} value [ts]``) are
  accepted on ``_bucket``/counter samples and their syntax validated;
* every family typed ``counter`` — histogram ``_bucket`` / ``_sum`` /
  ``_count`` children included — is monotonically non-decreasing
  between two scrapes with inference traffic in between.

Run directly (``python tools/metrics_lint.py``) or from
tools/ci_check.sh; exits non-zero with one line per violation.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import Dict, List, Optional, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# One label pair: name="value" with only escaped specials inside.
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\.)*)"')
# OpenMetrics exemplar suffix on a sample line:
#   ``... 42 # {trace_id="abc"} 95.0 1690000000.000``
_EXEMPLAR = re.compile(
    r"\s#\s*\{(?P<labels>[^}]*)\}\s+(?P<value>\S+)(?:\s+(?P<ts>\S+))?\s*$")

# Suffixes a histogram-typed family's child series may use.
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def histogram_base(family: str, types: Dict[str, str]) -> Optional[str]:
    """The histogram family ``family`` is a child series of (e.g.
    ``tpu_request_duration_us_bucket`` -> ``tpu_request_duration_us``)
    or None when it is not a histogram child."""
    for suffix in _HISTOGRAM_SUFFIXES:
        if family.endswith(suffix):
            base = family[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return None


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$")


def _parse_sample(line: str):
    """(family, labels_str, value_str, exemplar_str_or_None) or None
    when not a sample. An exemplar suffix is split off first so the
    value regex never sees it — but only when the remainder still
    parses as a sample: an ESCAPED label value may legally contain
    ``# {...}`` (tenant identity is client-supplied), and such a line
    is one long sample, not a sample plus exemplar."""
    exemplar = _EXEMPLAR.search(line)
    if exemplar is not None:
        m = _SAMPLE_RE.match(line[: exemplar.start()])
        if m is not None:
            return (m.group("name"), m.group("labels") or "",
                    m.group("value"), exemplar)
    m = _SAMPLE_RE.match(line)
    if m is None:
        return None
    return m.group("name"), m.group("labels") or "", m.group("value"), None


def lint_exposition(text: str) -> Tuple[List[str], Dict[str, str],
                                        Dict[Tuple[str, str], float]]:
    """Lints one exposition payload. Returns (errors, {family: type},
    {(family, labels): value})."""
    errors: List[str] = []
    help_seen: Dict[str, int] = {}
    type_seen: Dict[str, str] = {}
    first_sample: Dict[str, int] = {}
    series: Dict[Tuple[str, str], float] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                errors.append("line %d: HELP without text: %r"
                              % (lineno, line))
                continue
            family = parts[2]
            if family in help_seen:
                errors.append("line %d: duplicate HELP for %s"
                              % (lineno, family))
            help_seen.setdefault(family, lineno)
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append("line %d: malformed TYPE: %r"
                              % (lineno, line))
                continue
            family, kind = parts[2], parts[3]
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                errors.append("line %d: unknown TYPE %r for %s"
                              % (lineno, kind, family))
            if family in type_seen:
                errors.append("line %d: duplicate TYPE for %s"
                              % (lineno, family))
            type_seen.setdefault(family, kind)
            continue
        if line.startswith("#"):
            continue
        sample = _parse_sample(line)
        if sample is None:
            errors.append("line %d: unparseable sample: %r"
                          % (lineno, line))
            continue
        family, labels_str, value_str, exemplar = sample
        if exemplar is not None:
            # Exemplars are only meaningful on bucket/counter samples;
            # syntax: labels parse like sample labels, value is a
            # float, optional timestamp is a float.
            if not (family.endswith("_bucket")
                    or family.endswith("_total")):
                errors.append(
                    "line %d: exemplar on non-bucket/counter sample %s"
                    % (lineno, family))
            ex_labels = exemplar.group("labels")
            consumed = _LABEL_PAIR.sub("", ex_labels)
            if consumed.replace(",", "").strip():
                errors.append(
                    "line %d: malformed exemplar labels {%s}"
                    % (lineno, ex_labels))
            try:
                float(exemplar.group("value"))
                if exemplar.group("ts") is not None:
                    float(exemplar.group("ts"))
            except ValueError:
                errors.append("line %d: non-numeric exemplar value in "
                              "%r" % (lineno, line))
        first_sample.setdefault(family, lineno)
        if not _NAME.match(family):
            errors.append("line %d: illegal family name %r"
                          % (lineno, family))
        if labels_str:
            consumed = _LABEL_PAIR.sub("", labels_str)
            if consumed.replace(",", "").strip():
                errors.append(
                    "line %d: malformed/unescaped labels in %s{%s}"
                    % (lineno, family, labels_str))
            for label_name, _value in _LABEL_PAIR.findall(labels_str):
                if not _LABEL_NAME.match(label_name):
                    errors.append("line %d: illegal label name %r"
                                  % (lineno, label_name))
        try:
            value = float(value_str)
        except ValueError:
            errors.append("line %d: non-numeric value %r for %s"
                          % (lineno, value_str, family))
            continue
        key = (family, labels_str)
        if key in series:
            errors.append("line %d: duplicate series %s{%s}"
                          % (lineno, family, labels_str))
        series[key] = value
    for family, lineno in first_sample.items():
        # Histogram child series (_bucket/_sum/_count) are covered by
        # their base family's HELP/TYPE lines.
        base = histogram_base(family, type_seen) or family
        if base not in help_seen:
            errors.append("family %s has samples but no HELP" % base)
        elif help_seen[base] > lineno:
            errors.append("family %s: HELP appears after its first "
                          "sample" % base)
        if base not in type_seen:
            errors.append("family %s has samples but no TYPE" % base)
        if family.endswith("_total") and \
                type_seen.get(family, "counter") != "counter":
            errors.append("family %s ends in _total but is typed %s"
                          % (family, type_seen.get(family)))
    errors.extend(check_histograms(type_seen, series))
    # TYPE-before-sample ordering (re-scan cheaply).
    type_line: Dict[str, int] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        if raw.startswith("# TYPE "):
            parts = raw.split()
            if len(parts) >= 3:
                type_line.setdefault(parts[2], lineno)
    for family, lineno in first_sample.items():
        base = histogram_base(family, type_seen) or family
        if base in type_line and type_line[base] > lineno:
            errors.append("family %s: TYPE appears after its first "
                          "sample" % base)
    # Histogram children are cumulative like counters: expose them as
    # such so check_monotonic covers _bucket/_sum/_count across
    # scrapes (a bucket count that DROPS means lost observations).
    effective_types = dict(type_seen)
    for family in first_sample:
        if histogram_base(family, type_seen) is not None:
            effective_types[family] = "counter"
    return errors, effective_types, series


def _le_of(labels_str: str) -> Optional[str]:
    for name, value in _LABEL_PAIR.findall(labels_str):
        if name == "le":
            return value
    return None


def _strip_le(labels_str: str) -> str:
    pairs = [(name, value)
             for name, value in _LABEL_PAIR.findall(labels_str)
             if name != "le"]
    return ",".join('%s="%s"' % pair for pair in pairs)


def check_histograms(types: Dict[str, str],
                     series: Dict[Tuple[str, str], float]) -> List[str]:
    """Structural validation of every histogram family in one scrape:
    per label set, ``le`` bounds parse (``+Inf`` included) and are
    unique, cumulative counts are non-decreasing in ``le``, the ladder
    ends in ``+Inf``, ``_count`` equals the ``+Inf`` bucket, and
    ``_sum`` exists."""
    errors: List[str] = []
    histograms = [f for f, kind in types.items() if kind == "histogram"]
    for base in histograms:
        groups: Dict[str, List[Tuple[float, float]]] = {}
        sums: Dict[str, float] = {}
        counts: Dict[str, float] = {}
        for (family, labels_str), value in series.items():
            if family == base + "_sum":
                sums[labels_str] = value
                continue
            if family == base + "_count":
                counts[labels_str] = value
                continue
            if family != base + "_bucket":
                continue
            le = _le_of(labels_str)
            if le is None:
                errors.append("histogram %s: bucket sample without an "
                              "le label {%s}" % (base, labels_str))
                continue
            try:
                bound = float("inf") if le == "+Inf" else float(le)
            except ValueError:
                errors.append("histogram %s: unparseable le=%r"
                              % (base, le))
                continue
            groups.setdefault(_strip_le(labels_str), []).append(
                (bound, value))
        if not groups and (sums or counts):
            errors.append("histogram %s has _sum/_count but no "
                          "_bucket series" % base)
        for group, buckets in groups.items():
            bounds = [b for b, _ in buckets]
            if len(set(bounds)) != len(bounds):
                errors.append("histogram %s{%s}: duplicate le bounds"
                              % (base, group))
            buckets.sort(key=lambda pair: pair[0])
            if not buckets or buckets[-1][0] != float("inf"):
                errors.append("histogram %s{%s}: bucket ladder does "
                              "not end in le=\"+Inf\"" % (base, group))
            last = -1.0
            for bound, value in buckets:
                if value < last:
                    errors.append(
                        "histogram %s{%s}: cumulative bucket count "
                        "decreases at le=%s (%s -> %s)"
                        % (base, group, "+Inf" if bound == float("inf")
                           else bound, last, value))
                last = value
            if group not in sums:
                errors.append("histogram %s{%s}: missing _sum series"
                              % (base, group))
            if group not in counts:
                errors.append("histogram %s{%s}: missing _count series"
                              % (base, group))
            elif buckets and buckets[-1][0] == float("inf") \
                    and counts[group] != buckets[-1][1]:
                errors.append(
                    "histogram %s{%s}: _count %s != +Inf bucket %s"
                    % (base, group, counts[group], buckets[-1][1]))
    return errors


def check_monotonic(types: Dict[str, str],
                    before: Dict[Tuple[str, str], float],
                    after: Dict[Tuple[str, str], float]) -> List[str]:
    """Counter series must never decrease between two scrapes of the
    same live server."""
    errors = []
    for key, value in after.items():
        family, labels = key
        if types.get(family) != "counter":
            continue
        prior = before.get(key)
        if prior is not None and value < prior:
            errors.append(
                "counter %s{%s} decreased between scrapes: %s -> %s"
                % (family, labels, prior, value))
    return errors


def _drive_load(core, model_name: str, n: int, threads: int) -> None:
    """Concurrent inference bursts so the second scrape sees moving
    counters (incl. cache hits/misses and fused-batch families)."""
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    def request(seed: int, batched: bool):
        shape = [1, 16] if batched else [16]
        a = np.full(shape, seed % 97, dtype=np.int32)
        b = np.arange(16, dtype=np.int32).reshape(shape)
        t0 = InferInput("INPUT0", shape, "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", shape, "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=model_name,
                                     inputs=[t0, t1], outputs=None)

    batched = int(getattr(core.repository.get(model_name),
                          "max_batch_size", 0)) > 0

    def worker(offset: int):
        for i in range(n):
            core.infer(request(offset * 1000 + i, batched))

    pool = [threading.Thread(target=worker, args=(i,))
            for i in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()


def _drive_stream_load(core, n: int = 8) -> None:
    """Streaming traffic so the tpu_stream_* telemetry families
    populate: decoupled streams against repeat_int32 (real TTFT + ITL
    gaps) plus unary-through-stream against simple (one-response
    streams, TTFT only)."""
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    for i in range(n):
        request = get_inference_request(
            model_name="repeat_int32", inputs=[], outputs=None)
        tensor = request.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "INT32"
        tensor.shape.extend([4])
        request.raw_input_contents.append(
            np.arange(i, i + 4, dtype=np.int32).tobytes())
        for _ in core.stream_infer(request):
            pass
    shape = [16]
    a = np.full(shape, 7, dtype=np.int32)
    b = np.arange(16, dtype=np.int32)
    t0 = InferInput("INPUT0", shape, "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", shape, "INT32")
    t1.set_data_from_numpy(b)
    request = get_inference_request(model_name="simple",
                                    inputs=[t0, t1], outputs=None)
    for _ in range(n):
        for _ in core.stream_infer(request):
            pass


# Histogram families the telemetry layer must expose once unary AND
# streaming load has run (the ci_check gate that the SLO surface is
# actually present, not just lint-clean when absent).
EXPECTED_HISTOGRAMS = (
    "tpu_request_duration_us",
    "tpu_stage_duration_us",
    "tpu_stream_first_response_us",
    "tpu_stream_inter_response_us",
)

# SLO families that must render once an slo-declaring model has served
# traffic (gauges, so only presence is checked — burn values are the
# flight smoke's business).
EXPECTED_SLO_FAMILIES = (
    "tpu_slo_target",
    "tpu_slo_burn_rate",
    "tpu_slo_budget_remaining",
    "tpu_slo_healthy",
)


# -- /v2/debug snapshot lint -------------------------------------------------

# Dict keys that look like per-request/per-trace identities: a JSON
# snapshot keyed by them grows without bound (request ids, trace ids,
# uuids, correlation ids). Identities belong in list VALUES (bounded
# by what is live/kept), never as dict keys.
_IDENTITY_KEY = re.compile(r"^(?:[0-9a-f]{12,}|[0-9]{7,}|"
                           r"[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-"
                           r"[0-9a-f]{4}-[0-9a-f]{12})$")

# Fan-out bounds: a debug snapshot is an operator page, not a dump.
MAX_DEBUG_DICT_KEYS = 2048
MAX_DEBUG_LIST_ITEMS = 8192


def lint_debug_snapshot(doc, path: str = "$") -> List[str]:
    """Walks a /v2/debug (or /v2/debug/flight) JSON document and flags
    unbounded-cardinality shapes: dicts keyed by request/trace-like
    identities, dicts fanning out past MAX_DEBUG_DICT_KEYS, and lists
    past MAX_DEBUG_LIST_ITEMS. Run in CI against a loaded server so a
    new debug section cannot silently key itself on a per-request
    value."""
    errors: List[str] = []
    if isinstance(doc, dict):
        if len(doc) > MAX_DEBUG_DICT_KEYS:
            errors.append("%s: dict fans out to %d keys (max %d)"
                          % (path, len(doc), MAX_DEBUG_DICT_KEYS))
        for key, value in doc.items():
            key_str = str(key)
            if _IDENTITY_KEY.match(key_str.lower()):
                errors.append(
                    "%s: dict key %r looks like a per-request/trace "
                    "identity — unbounded cardinality (identities "
                    "belong in list values)" % (path, key_str))
            errors.extend(lint_debug_snapshot(
                value, "%s.%s" % (path, key_str)))
    elif isinstance(doc, list):
        if len(doc) > MAX_DEBUG_LIST_ITEMS:
            errors.append("%s: list holds %d items (max %d)"
                          % (path, len(doc), MAX_DEBUG_LIST_ITEMS))
        for index, value in enumerate(doc[:MAX_DEBUG_LIST_ITEMS]):
            errors.extend(lint_debug_snapshot(
                value, "%s[%d]" % (path, index)))
    return errors


def main() -> int:
    from client_tpu.server.app import build_core

    core = build_core(["simple", "simple_cache", "simple_replicas",
                       "simple_slo", "repeat_int32"])
    try:
        _drive_load(core, "simple", n=20, threads=2)
        _drive_load(core, "simple_cache", n=20, threads=2)
        # simple_replicas exercises the tpu_replica_* families (health
        # gauges + per-replica exec counters) under fused dispatch.
        _drive_load(core, "simple_replicas", n=20, threads=4)
        # simple_slo declares an `slo` block, so the tpu_slo_*
        # families render (and the scrape itself advances the burn
        # windows).
        _drive_load(core, "simple_slo", n=20, threads=2)
        _drive_stream_load(core)
        first = core.metrics_text()
        errors, types, series_before = lint_exposition(first)
        # More traffic between the scrapes, half of it replayed so the
        # cache-hit counters move too.
        _drive_load(core, "simple", n=20, threads=4)
        _drive_load(core, "simple_cache", n=20, threads=4)
        _drive_load(core, "simple_replicas", n=20, threads=4)
        _drive_load(core, "simple_slo", n=20, threads=2)
        _drive_stream_load(core)
        second = core.metrics_text()
        errors2, types2, series_after = lint_exposition(second)
        errors.extend(e for e in errors2 if e not in errors)
        errors.extend(check_monotonic(types2, series_before, series_after))
        for family in EXPECTED_HISTOGRAMS:
            if types2.get(family) != "histogram":
                errors.append(
                    "expected histogram family %s missing from the "
                    "exposition under streaming load" % family)
        for family in EXPECTED_SLO_FAMILIES:
            if types2.get(family) != "gauge":
                errors.append(
                    "expected SLO gauge family %s missing from the "
                    "exposition (simple_slo declares an slo block)"
                    % family)
        if types2.get("tpu_server_info") != "gauge":
            errors.append("tpu_server_info gauge missing from the "
                          "exposition")
        # Device-axis families (server/devstats.py): busy time must
        # accumulate from the load driven above, and the scrape-error
        # counter renders unconditionally.
        if types2.get("tpu_device_busy_us_total") != "counter":
            errors.append("tpu_device_busy_us_total counter missing "
                          "from the exposition under load")
        if types2.get("tpu_device_stats_errors_total") != "counter":
            errors.append("tpu_device_stats_errors_total counter "
                          "missing from the exposition")
        # The /v2/debug snapshot (and the flight dump) must stay
        # cardinality-bounded: no dict keyed by request/trace ids, no
        # unbounded fan-out.
        debug_errors = lint_debug_snapshot(core.debug_snapshot())
        errors.extend("debug: %s" % e for e in debug_errors)
        flight_errors = lint_debug_snapshot(core.debug_flight())
        errors.extend("debug/flight: %s" % e for e in flight_errors)
        # The negotiated OpenMetrics flavor (exemplars + '# EOF') must
        # lint clean too, and the PLAIN flavor must never leak
        # exemplar syntax — stock text-format parsers reject it.
        openmetrics = core.metrics_text(openmetrics=True)
        errors3, _, _ = lint_exposition(openmetrics)
        errors.extend("openmetrics: %s" % e for e in errors3
                      if "openmetrics: %s" % e not in errors)
        if not openmetrics.rstrip().endswith("# EOF"):
            errors.append("openmetrics flavor missing the # EOF "
                          "terminator")
        if "# {" in second:
            errors.append("plain text-format flavor leaked exemplar "
                          "syntax")
        moved = sum(
            1 for key, value in series_after.items()
            if types2.get(key[0]) == "counter"
            and value > series_before.get(key, 0.0))
        if moved == 0:
            errors.append("no counter series advanced between scrapes "
                          "under load — the exposition looks frozen")
    finally:
        core.shutdown()
    if errors:
        for error in errors:
            print("metrics lint: %s" % error, file=sys.stderr)
        print("metrics lint FAILED (%d violation%s)"
              % (len(errors), "s" if len(errors) != 1 else ""),
              file=sys.stderr)
        return 1
    print("metrics lint passed: %d families, %d series, %d counters "
          "advanced under load"
          % (len(types2), len(series_after), moved))
    return 0


if __name__ == "__main__":
    sys.exit(main())
