#!/usr/bin/env python
"""Prometheus exposition lint for ``core.metrics_text()``.

Validates the /metrics surface the perf MetricsManager and external
scrapers consume, then proves counter monotonicity across two scrapes
taken under concurrent load:

* every sample's family has a ``# HELP`` and ``# TYPE`` line, and both
  appear BEFORE the family's first sample (Prometheus exposition
  format requirement);
* family/label names are legal, label values are properly escaped
  (no raw ``"``, ``\\`` or newline inside a quoted value);
* no duplicate series (family + label set appears once per scrape);
* ``_total``-suffixed families are typed ``counter``;
* every family typed ``counter`` is monotonically non-decreasing
  between two scrapes with inference traffic in between.

Run directly (``python tools/metrics_lint.py``) or from
tools/ci_check.sh; exits non-zero with one line per violation.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import Dict, List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# One label pair: name="value" with only escaped specials inside.
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\.)*)"')


def _parse_sample(line: str):
    """(family, labels_str, value_str) or None when not a sample."""
    m = re.match(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$", line)
    if m is None:
        return None
    return m.group("name"), m.group("labels") or "", m.group("value")


def lint_exposition(text: str) -> Tuple[List[str], Dict[str, str],
                                        Dict[Tuple[str, str], float]]:
    """Lints one exposition payload. Returns (errors, {family: type},
    {(family, labels): value})."""
    errors: List[str] = []
    help_seen: Dict[str, int] = {}
    type_seen: Dict[str, str] = {}
    first_sample: Dict[str, int] = {}
    series: Dict[Tuple[str, str], float] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                errors.append("line %d: HELP without text: %r"
                              % (lineno, line))
                continue
            family = parts[2]
            if family in help_seen:
                errors.append("line %d: duplicate HELP for %s"
                              % (lineno, family))
            help_seen.setdefault(family, lineno)
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append("line %d: malformed TYPE: %r"
                              % (lineno, line))
                continue
            family, kind = parts[2], parts[3]
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                errors.append("line %d: unknown TYPE %r for %s"
                              % (lineno, kind, family))
            if family in type_seen:
                errors.append("line %d: duplicate TYPE for %s"
                              % (lineno, family))
            type_seen.setdefault(family, kind)
            continue
        if line.startswith("#"):
            continue
        sample = _parse_sample(line)
        if sample is None:
            errors.append("line %d: unparseable sample: %r"
                          % (lineno, line))
            continue
        family, labels_str, value_str = sample
        first_sample.setdefault(family, lineno)
        if not _NAME.match(family):
            errors.append("line %d: illegal family name %r"
                          % (lineno, family))
        if labels_str:
            consumed = _LABEL_PAIR.sub("", labels_str)
            if consumed.replace(",", "").strip():
                errors.append(
                    "line %d: malformed/unescaped labels in %s{%s}"
                    % (lineno, family, labels_str))
            for label_name, _value in _LABEL_PAIR.findall(labels_str):
                if not _LABEL_NAME.match(label_name):
                    errors.append("line %d: illegal label name %r"
                                  % (lineno, label_name))
        try:
            value = float(value_str)
        except ValueError:
            errors.append("line %d: non-numeric value %r for %s"
                          % (lineno, value_str, family))
            continue
        key = (family, labels_str)
        if key in series:
            errors.append("line %d: duplicate series %s{%s}"
                          % (lineno, family, labels_str))
        series[key] = value
    for family, lineno in first_sample.items():
        if family not in help_seen:
            errors.append("family %s has samples but no HELP" % family)
        elif help_seen[family] > lineno:
            errors.append("family %s: HELP appears after its first "
                          "sample" % family)
        if family not in type_seen:
            errors.append("family %s has samples but no TYPE" % family)
        if family.endswith("_total") and \
                type_seen.get(family, "counter") != "counter":
            errors.append("family %s ends in _total but is typed %s"
                          % (family, type_seen.get(family)))
    # TYPE-before-sample ordering (re-scan cheaply).
    type_line: Dict[str, int] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        if raw.startswith("# TYPE "):
            parts = raw.split()
            if len(parts) >= 3:
                type_line.setdefault(parts[2], lineno)
    for family, lineno in first_sample.items():
        if family in type_line and type_line[family] > lineno:
            errors.append("family %s: TYPE appears after its first "
                          "sample" % family)
    return errors, type_seen, series


def check_monotonic(types: Dict[str, str],
                    before: Dict[Tuple[str, str], float],
                    after: Dict[Tuple[str, str], float]) -> List[str]:
    """Counter series must never decrease between two scrapes of the
    same live server."""
    errors = []
    for key, value in after.items():
        family, labels = key
        if types.get(family) != "counter":
            continue
        prior = before.get(key)
        if prior is not None and value < prior:
            errors.append(
                "counter %s{%s} decreased between scrapes: %s -> %s"
                % (family, labels, prior, value))
    return errors


def _drive_load(core, model_name: str, n: int, threads: int) -> None:
    """Concurrent inference bursts so the second scrape sees moving
    counters (incl. cache hits/misses and fused-batch families)."""
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    def request(seed: int, batched: bool):
        shape = [1, 16] if batched else [16]
        a = np.full(shape, seed % 97, dtype=np.int32)
        b = np.arange(16, dtype=np.int32).reshape(shape)
        t0 = InferInput("INPUT0", shape, "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", shape, "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=model_name,
                                     inputs=[t0, t1], outputs=None)

    batched = int(getattr(core.repository.get(model_name),
                          "max_batch_size", 0)) > 0

    def worker(offset: int):
        for i in range(n):
            core.infer(request(offset * 1000 + i, batched))

    pool = [threading.Thread(target=worker, args=(i,))
            for i in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()


def main() -> int:
    from client_tpu.server.app import build_core

    core = build_core(["simple", "simple_cache", "simple_replicas"])
    try:
        _drive_load(core, "simple", n=20, threads=2)
        _drive_load(core, "simple_cache", n=20, threads=2)
        # simple_replicas exercises the tpu_replica_* families (health
        # gauges + per-replica exec counters) under fused dispatch.
        _drive_load(core, "simple_replicas", n=20, threads=4)
        first = core.metrics_text()
        errors, types, series_before = lint_exposition(first)
        # More traffic between the scrapes, half of it replayed so the
        # cache-hit counters move too.
        _drive_load(core, "simple", n=20, threads=4)
        _drive_load(core, "simple_cache", n=20, threads=4)
        _drive_load(core, "simple_replicas", n=20, threads=4)
        second = core.metrics_text()
        errors2, types2, series_after = lint_exposition(second)
        errors.extend(e for e in errors2 if e not in errors)
        errors.extend(check_monotonic(types2, series_before, series_after))
        moved = sum(
            1 for key, value in series_after.items()
            if types2.get(key[0]) == "counter"
            and value > series_before.get(key, 0.0))
        if moved == 0:
            errors.append("no counter series advanced between scrapes "
                          "under load — the exposition looks frozen")
    finally:
        core.shutdown()
    if errors:
        for error in errors:
            print("metrics lint: %s" % error, file=sys.stderr)
        print("metrics lint FAILED (%d violation%s)"
              % (len(errors), "s" if len(errors) != 1 else ""),
              file=sys.stderr)
        return 1
    print("metrics lint passed: %d families, %d series, %d counters "
          "advanced under load"
          % (len(types2), len(series_after), moved))
    return 0


if __name__ == "__main__":
    sys.exit(main())
