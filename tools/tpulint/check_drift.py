"""proto-drift and metrics-doc-drift checkers.

proto-drift: the schema exists in three places that are edited by
hand — the ``.proto`` sources, the serialized descriptors embedded in
``*_pb2.py`` (patched by ``tools/extend_inference_proto.py``, protoc
is not in the image), and the patch lists inside that tool. All three
must agree on every patched (message, field, number) triple, and the
``.proto`` text must be syntactically sane (PR 8 shipped a ``/``
comment that is invalid protobuf and broke downstream protoc users).

metrics-doc-drift: every ``tpu_*`` Prometheus family registered by
the server (``family("tpu_…", …)`` calls in ``client_tpu/server/``)
must be documented in the docs/metrics.md catalog, and every
``tpu_*`` family the catalog lists must still be emitted."""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, List, Set, Tuple

from tools.tpulint.framework import Finding

_PROTO_DIR = "client_tpu/protocol"


def _normalize_rows(rows) -> List[Tuple[str, int]]:
    return [(row[0], row[1]) for row in rows]


def _expected_schema():
    """(message -> [(field, number)]) per proto file, sourced from the
    patch lists in tools/extend_inference_proto.py so the tool itself
    is one of the three compared artifacts."""
    import tools.extend_inference_proto as tool

    inference = {
        "BatchPipelineStatistics": _normalize_rows(tool.PIPELINE_FIELDS),
        "ModelStatistics": (
            _normalize_rows(tool.STATISTICS_FIELDS)
            + _normalize_rows(tool.CACHE_COUNT_FIELDS)
            + _normalize_rows(tool.QOS_COUNT_FIELDS)
            + _normalize_rows(tool.REPLICA_COUNT_FIELDS)
            + [("pipeline_stats", 8), ("sequence_stats", 11),
               ("priority_stats", 15), ("tenant_stats", 16),
               ("replica_stats", 17), ("stream_stats", 20),
               ("slo_stats", 21), ("device_stats", 22)]),
        "SequenceBatchingStatistics":
            _normalize_rows(tool.SEQUENCE_STATS_FIELDS),
        "PriorityStatistics": _normalize_rows(tool.PRIORITY_STATS_FIELDS),
        "TenantStatistics": _normalize_rows(tool.TENANT_STATS_FIELDS),
        "ReplicaStatistics": _normalize_rows(tool.REPLICA_STATS_FIELDS),
        "StreamStatistics": _normalize_rows(tool.STREAM_STATS_FIELDS),
        "SloStatistics": _normalize_rows(tool.SLO_STATS_FIELDS),
        "DeviceHbmComponent":
            _normalize_rows(tool.DEVICE_HBM_COMPONENT_FIELDS),
        "DeviceStatistics": (
            _normalize_rows(tool.DEVICE_STATS_FIELDS)
            + [("components", 2)]),
        "InferStatistics": _normalize_rows(tool.CACHE_DURATION_FIELDS),
    }
    model_config = {
        "DynamicBatchingConfig": (
            _normalize_rows(tool.QUEUE_POLICY_FIELDS)
            + _normalize_rows(tool.PRIORITY_FIELDS)
            + [("priority_queue_policy", 9)]),
        "PriorityQueuePolicy": _normalize_rows(tool.PRIORITY_POLICY_FIELDS),
        "SequenceControlInput": _normalize_rows(tool.CONTROL_INPUT_FIELDS),
        "SequenceStateConfig": _normalize_rows(tool.STATE_CONFIG_FIELDS),
        "SequenceBatchingConfig":
            _normalize_rows(tool.SEQUENCE_BATCHING_FIELDS),
        "ResponseCacheConfig": [("enable", 1)],
        "SloConfig": _normalize_rows(tool.SLO_CONFIG_FIELDS),
        "AutoscaleConfig": _normalize_rows(tool.AUTOSCALE_CONFIG_FIELDS),
        "ModelInstanceConfig": [("autoscale", 5), ("shard_mesh", 6)],
        "ModelConfig": [("response_cache", 15), ("slo", 16)],
    }
    return {
        ("inference.proto", "inference_pb2.py"): inference,
        ("model_config.proto", "model_config_pb2.py"): model_config,
    }


def _pb2_fields(pb2_path: pathlib.Path) -> Dict[str, Dict[str, int]]:
    """message -> {field: number} parsed from the serialized
    FileDescriptorProto embedded in a *_pb2.py."""
    from google.protobuf import descriptor_pb2

    import tools.extend_inference_proto as tool

    source = pb2_path.read_text()
    file_proto = descriptor_pb2.FileDescriptorProto()
    file_proto.ParseFromString(tool.extract_serialized(source, pb2_path))
    result: Dict[str, Dict[str, int]] = {}
    for message in file_proto.message_type:
        result[message.name] = {f.name: f.number for f in message.field}
    return result


def _proto_message_blocks(text: str) -> Dict[str, str]:
    """message name -> body text (outermost messages, brace-matched)."""
    blocks: Dict[str, str] = {}
    for match in re.finditer(r"\bmessage\s+(\w+)\s*\{", text):
        depth = 1
        i = match.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        blocks[match.group(1)] = text[match.end():i]
    return blocks


def _strip_proto_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", lambda m: re.sub(r"[^\n]", " ", m.group()),
                  text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def check_proto_drift(root: pathlib.Path) -> List[Finding]:
    findings: List[Finding] = []
    schema = _expected_schema()
    for (proto_name, pb2_name), messages in schema.items():
        proto_path = root / _PROTO_DIR / proto_name
        pb2_path = root / _PROTO_DIR / pb2_name
        rel_proto = "%s/%s" % (_PROTO_DIR, proto_name)
        rel_pb2 = "%s/%s" % (_PROTO_DIR, pb2_name)
        if not proto_path.exists() or not pb2_path.exists():
            findings.append(Finding(
                "proto-drift", rel_proto, 1,
                "expected proto/pb2 pair missing on disk"))
            continue
        proto_text = proto_path.read_text()
        findings.extend(_proto_syntax(proto_text, rel_proto))
        stripped = _strip_proto_comments(proto_text)
        blocks = _proto_message_blocks(stripped)
        try:
            pb2_messages = _pb2_fields(pb2_path)
        except Exception as e:  # noqa: BLE001 — a broken pb2 IS the finding
            findings.append(Finding(
                "proto-drift", rel_pb2, 1,
                "embedded descriptor failed to parse: %s" % e))
            continue
        for message, fields in messages.items():
            descriptor = pb2_messages.get(message)
            block = blocks.get(message)
            if descriptor is None:
                findings.append(Finding(
                    "proto-drift", rel_pb2, 1,
                    "message %s from the extend_inference_proto patch "
                    "list is absent from the pb2 descriptor — rerun "
                    "tools/extend_inference_proto.py" % message))
            if block is None:
                findings.append(Finding(
                    "proto-drift", rel_proto, 1,
                    "message %s from the extend_inference_proto patch "
                    "list is absent from the .proto source" % message))
            for field, number in fields:
                if descriptor is not None and \
                        descriptor.get(field) != number:
                    findings.append(Finding(
                        "proto-drift", rel_pb2, 1,
                        "%s.%s should be field %d per the patch list "
                        "but the pb2 descriptor has %s"
                        % (message, field, number,
                           descriptor.get(field, "no such field"))))
                if block is not None and not re.search(
                        r"\b%s\s*=\s*%d\s*[;\[]" % (re.escape(field),
                                                    number), block):
                    findings.append(Finding(
                        "proto-drift", rel_proto,
                        _line_of(proto_text, "message %s" % message),
                        "%s.%s = %d is in the patch list + pb2 but not "
                        "in the .proto source — the three are out of "
                        "sync" % (message, field, number)))
        # Duplicate field numbers inside one .proto message (nested
        # message/enum declarations have their own number space and
        # are stripped first; oneof members share the parent's).
        for message, block in blocks.items():
            numbers = re.findall(r"=\s*(\d+)\s*[;\[]",
                                 _strip_nested_blocks(block))
            dupes = {n for n in numbers if numbers.count(n) > 1}
            if dupes:
                findings.append(Finding(
                    "proto-drift", rel_proto,
                    _line_of(proto_text, "message %s" % message),
                    "duplicate field number(s) %s in message %s"
                    % (sorted(dupes), message)))
    return findings


def _strip_nested_blocks(body: str) -> str:
    """Remove nested ``message``/``enum`` declarations (their fields
    number independently of the parent's)."""
    out = []
    i = 0
    while i < len(body):
        match = re.compile(r"\b(message|enum)\s+\w+\s*\{").search(body, i)
        if match is None:
            out.append(body[i:])
            break
        out.append(body[i:match.start()])
        depth = 1
        j = match.end()
        while j < len(body) and depth:
            if body[j] == "{":
                depth += 1
            elif body[j] == "}":
                depth -= 1
            j += 1
        i = j
    return "".join(out)


def _line_of(text: str, needle: str) -> int:
    index = text.find(needle)
    if index < 0:
        return 1
    return text.count("\n", 0, index) + 1


def _proto_syntax(text: str, rel_path: str) -> List[Finding]:
    """The exact PR-8 defect class: a comment opened with a single
    ``/`` is invalid protobuf (protoc: 'Expected top-level statement').
    Also checks brace balance."""
    findings: List[Finding] = []
    in_block_comment = False
    for lineno, line in enumerate(text.splitlines(), 1):
        i = 0
        while i < len(line):
            if in_block_comment:
                end = line.find("*/", i)
                if end < 0:
                    break
                in_block_comment = False
                i = end + 2
                continue
            ch = line[i]
            if ch == '"':
                closing = line.find('"', i + 1)
                i = len(line) if closing < 0 else closing + 1
                continue
            if ch == "/":
                nxt = line[i + 1] if i + 1 < len(line) else ""
                if nxt == "/":
                    i = len(line)
                    continue
                if nxt == "*":
                    in_block_comment = True
                    i += 2
                    continue
                findings.append(Finding(
                    "proto-drift", rel_path, lineno,
                    "stray '/' — protobuf comments are '//' or '/* */' "
                    "(a '/' comment broke inference.proto in PR 8)"))
                i = len(line)
                continue
            i += 1
    stripped = _strip_proto_comments(text)
    if stripped.count("{") != stripped.count("}"):
        findings.append(Finding(
            "proto-drift", rel_path, 1,
            "unbalanced braces ({=%d, }=%d)"
            % (stripped.count("{"), stripped.count("}"))))
    return findings


# -- metrics <-> docs -------------------------------------------------------

_DOC_FAMILY = re.compile(r"^\|\s*`(tpu_[a-z0-9_]+)`")


def _emitted_families(root: pathlib.Path):
    """{family: (path, line)} for every family("tpu_…", …) call under
    client_tpu/server/."""
    emitted: Dict[str, Tuple[str, int]] = {}
    for path in sorted((root / "client_tpu" / "server").glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        rel = path.relative_to(root).as_posix()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "family" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str) and \
                        first.value.startswith("tpu_"):
                    emitted.setdefault(first.value, (rel, node.lineno))
    return emitted


def check_metrics_doc_drift(root: pathlib.Path) -> List[Finding]:
    findings: List[Finding] = []
    doc_path = root / "docs" / "metrics.md"
    rel_doc = "docs/metrics.md"
    if not doc_path.exists():
        return [Finding("metrics-doc-drift", rel_doc, 1,
                        "docs/metrics.md is missing")]
    documented: Dict[str, int] = {}
    for lineno, line in enumerate(doc_path.read_text().splitlines(), 1):
        match = _DOC_FAMILY.match(line.strip())
        if match:
            documented.setdefault(match.group(1), lineno)
    emitted = _emitted_families(root)
    for family, (path, line) in sorted(emitted.items()):
        if family not in documented:
            findings.append(Finding(
                "metrics-doc-drift", path, line,
                "registered family %s is not documented in "
                "docs/metrics.md" % family))
    for family, lineno in sorted(documented.items()):
        if family not in emitted:
            findings.append(Finding(
                "metrics-doc-drift", rel_doc, lineno,
                "docs/metrics.md documents %s but no "
                "client_tpu/server/ family() call registers it"
                % family))
    return findings
