"""resource-pairing checker.

Any ``acquire()`` / ``begin_*()`` call whose matching release
(``release()`` / ``finish_*()``) is not guaranteed by a ``finally``
block, an ``__exit__`` method, or a context manager is an error. This
is the exact shape of the PR-7 stream-path tenant-token leak: a
``repository.acquire`` that raised between a tenant-token spend and
its release permanently starved a concurrency-capped tenant.

Rules, per function:

* an acquire whose receiver also has a matching release call in the
  same function: at least one release site must be lexically inside a
  ``finally`` block (or an ``__exit__`` body). Success-path +
  except-handler releases do NOT count — that is precisely the shape
  that leaked.
* an acquire with NO matching release in the same function is an
  error too, unless the function is ``__enter__``/``__init__`` and the
  class's ``__exit__``/teardown methods release it, the result is
  stored on ``self`` (ownership handed to the object), or the
  function IS a generator (the caller's ``finally`` runs on close).

Plain lock mutexes are the lock-discipline/lock-order checkers'
domain and skipped here."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.tpulint.framework import (
    Finding,
    SourceFile,
    expr_text,
    is_lockish,
    iter_functions,
    own_nodes,
)

_RELEASE_OF = {
    "acquire": ("release",),
    "begin_unload": ("finish_unload", "unload"),
    # Device-ledger rows (client_tpu/server/devstats.py): a
    # ledger.register() whose row is never released leaks a
    # tpu_hbm_model_bytes row for the process lifetime — the same
    # guarantee class as the PR-7 tenant-admission slot. Scoped to
    # ledger-named receivers (see _acquire_attr) so unrelated
    # register() verbs (shm regions, prefix-cache pages) stay out.
    "register": ("release", "release_component", "release_model"),
    # HBM-allocator leases (client_tpu/server/hbm.py): an unpaired
    # HbmAllocator.lease() holds device-budget bytes for the process
    # lifetime — phantom pressure that evicts innocent models. Scoped
    # to hbm/alloc-named receivers (see _acquire_attr).
    "lease": ("release", "release_model"),
    # Weight paging: a pager.page_out() whose host state is neither
    # restored nor handed off strands a model's weights on the host
    # with the device bytes already freed. Scoped to pager-named
    # receivers.
    "page_out": ("restore", "release", "release_model"),
    # Cancel-callback registrations (client_tpu/server/cancel.py): an
    # on_cancel() handle that is never removed keeps the dead
    # request's closure — and whatever it captures: batcher pending
    # entries, scheduler lanes — alive on the token, and a late cancel
    # fires into state the request already tore down.
    "on_cancel": ("remove_callback",),
}

# Acquire verbs whose result assigned onto ANY attribute counts as an
# ownership hand-off (ledger rows / leases / host weight states ride
# resource objects — regions, leases, replicas — whose teardown path
# releases them).
_ATTRIBUTE_HANDOFF_VERBS = ("register", "lease", "page_out")


def _release_names(acquire_attr: str) -> Tuple[str, ...]:
    if acquire_attr in _RELEASE_OF:
        return _RELEASE_OF[acquire_attr]
    if acquire_attr.startswith("begin_"):
        return ("finish_" + acquire_attr[len("begin_"):],)
    return ()


def _acquire_attr(call: ast.Call) -> Optional[str]:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "register":
        receiver = expr_text(func.value).split(".")[-1]
        return func.attr if "ledger" in receiver.lower() else None
    if func.attr == "lease":
        receiver = expr_text(func.value).split(".")[-1].lower()
        return func.attr if ("hbm" in receiver or "alloc" in receiver) \
            else None
    if func.attr == "page_out":
        receiver = expr_text(func.value).split(".")[-1].lower()
        return func.attr if "pager" in receiver else None
    if func.attr == "on_cancel":
        return func.attr
    if func.attr == "acquire" or func.attr.startswith("begin_"):
        if is_lockish(func.value):
            return None  # mutexes are lock-discipline's domain
        return func.attr
    return None


def _is_generator(func: ast.AST) -> bool:
    return any(isinstance(node, (ast.Yield, ast.YieldFrom))
               for node in own_nodes(func))


def _assigned_to_self(stmt: Optional[ast.stmt]) -> bool:
    if not isinstance(stmt, ast.Assign):
        return False
    for target in stmt.targets:
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            return True
    return False


def _assigned_to_attribute(stmt: Optional[ast.stmt]) -> bool:
    """Ownership hand-off for ledger rows: ``region.ledger_row =
    ledger.register(...)`` parks the handle on the owning object,
    whose teardown path releases it — broader than the self-only rule
    because rows commonly ride resource objects (regions, replicas),
    not the registering class itself."""
    if not isinstance(stmt, ast.Assign):
        return False
    return any(isinstance(target, ast.Attribute)
               for target in stmt.targets)


def check_resource_pairing(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []

    # class -> set of (receiver_text, release_attr) released in
    # __exit__/close/stop/shutdown-style teardown methods.
    teardown_releases: Dict[str, Set[Tuple[str, str]]] = {}
    for _qual, cls, func in iter_functions(src.tree):
        if cls is None or func.name not in ("__exit__", "__aexit__",
                                            "close", "stop", "shutdown",
                                            "unload", "__del__"):
            continue
        for node in ast.walk(func):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                teardown_releases.setdefault(cls, set()).add(
                    (expr_text(node.func.value), node.func.attr))

    for qual, cls, func in iter_functions(src.tree):
        if func.name in ("__exit__", "__aexit__"):
            continue
        acquires = []  # (call, attr, receiver_text, enclosing_stmt)
        releases = []  # (receiver_text, attr, stmt)

        # Pair statements with their calls so we can ask "is this
        # release inside a finally suite". Both walks prune nested
        # function bodies — a nested def's acquires/releases belong to
        # that def's own visit, not the enclosing function's.
        for stmt in own_nodes(func):
            if not isinstance(stmt, ast.stmt):
                continue
            for node in own_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                attr = _acquire_attr(node)
                if attr is not None and not _inside_with(func, node):
                    acquires.append((node, attr,
                                     expr_text(node.func.value), stmt))
                if isinstance(node.func, ast.Attribute):
                    releases.append((expr_text(node.func.value),
                                     node.func.attr, stmt))

        # Deduplicate: ast.walk reaches each call through every
        # enclosing statement; keep the innermost statement per call.
        seen = {}
        for call, attr, receiver, stmt in acquires:
            seen[id(call)] = (call, attr, receiver, stmt)
        acquires = list(seen.values())

        for call, attr, receiver, stmt in acquires:
            wanted = _release_names(attr)
            # A release lexically BEFORE the acquire cannot be its
            # pairing — that is the replace pattern (drop the previous
            # holder's row, then register the fresh one), and treating
            # it as a pairing would demand a nonsensical finally.
            matching = [(r_receiver, r_attr, r_stmt)
                        for r_receiver, r_attr, r_stmt in releases
                        if r_attr in wanted and _receivers_match(
                            receiver, r_receiver)
                        and r_stmt.lineno >= call.lineno]
            if matching:
                if any(_stmt_in_finally_chain(func, r_stmt)
                       for _r, _a, r_stmt in matching):
                    continue
                findings.append(src.finding(
                    "resource-pairing", call,
                    "%s.%s() is released in this function but never "
                    "inside a finally: an exception between the two "
                    "leaks the %s" % (receiver, attr,
                                      _resource_noun(attr))))
                continue
            # No release here: excused hand-off patterns.
            if attr in _ATTRIBUTE_HANDOFF_VERBS and \
                    _assigned_to_attribute(stmt):
                continue
            if _assigned_to_self(stmt):
                continue
            if _is_generator(func):
                continue
            if func.name in ("__enter__", "__init__", "start"):
                excused = cls is not None and any(
                    r_attr in wanted and _receivers_match(receiver, r_recv)
                    for r_recv, r_attr in teardown_releases.get(cls, ()))
                if excused:
                    continue
            findings.append(src.finding(
                "resource-pairing", call,
                "%s.%s() has no matching %s in this function (nor a "
                "teardown hand-off): the %s leaks on every path"
                % (receiver, attr, "/".join(wanted) or "release",
                   _resource_noun(attr))))
    return findings


def _resource_noun(attr: str) -> str:
    if attr == "acquire":
        return "model/token slot"
    if attr == "lease":
        return "HBM lease"
    if attr == "page_out":
        return "paged-out weight state"
    if attr == "on_cancel":
        return "cancel-callback handle"
    return "drain state"


def _receivers_match(a: str, b: str) -> bool:
    """``self.repository`` vs ``repository`` vs ``self._core.repository``
    should pair: compare on the final attribute component. A suffix
    match also pairs (``quotas`` acquired, ``tenant_quotas``
    released) — local aliases commonly shorten the attribute name."""
    last_a, last_b = a.split(".")[-1], b.split(".")[-1]
    return last_a == last_b or last_a.endswith(last_b) or \
        last_b.endswith(last_a)


def _inside_with(func: ast.AST, call: ast.Call) -> bool:
    """True when the acquire call IS a with-item context expression
    (``with pool.acquire() as x:`` releases via __exit__)."""
    for node in ast.walk(func):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if sub is call:
                        return True
    return False


def _stmt_in_finally_chain(func: ast.AST, stmt: ast.stmt) -> bool:
    """True when ``stmt`` lives (at any depth) inside some Try's
    finalbody within ``func``."""
    for node in ast.walk(func):
        if isinstance(node, ast.Try):
            for final_stmt in node.finalbody:
                for sub in ast.walk(final_stmt):
                    if sub is stmt:
                        return True
    return False
