"""Classification of blocking calls, shared by the lock-discipline and
aio-blocking checkers.

The list is grounded in what has actually burned this repo: PR 6 had
to move record rendering outside ``_trace_lock``; the batcher/replica
web mixes device work with bucket locks; and the aio clients must not
run sync sleeps/sockets on the event loop."""

from __future__ import annotations

import ast
from typing import Optional

from tools.tpulint.framework import expr_text, terminal_name

# Module-level callables that block the calling thread.
_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"): "time.sleep blocks the thread",
    ("socket", "create_connection"): "socket connect is unbounded I/O",
    ("subprocess", "run"): "subprocess.run blocks on the child",
    ("subprocess", "check_output"): "subprocess.check_output blocks",
    ("subprocess", "check_call"): "subprocess.check_call blocks",
    ("subprocess", "call"): "subprocess.call blocks",
    ("jax", "device_get"): "jax device->host transfer stalls on the device",
    ("jax", "device_put"): "jax host->device transfer stalls on the device",
}

# Bare-name calls (``from time import sleep``-style imports).
_BLOCKING_NAME_CALLS = {
    "sleep": "sleep blocks the thread",
    "urlopen": "urlopen is unbounded network I/O",
}

# Method names that are blocking regardless of the receiver.
_BLOCKING_METHODS = {
    "recv": "socket recv blocks on the peer",
    "recv_into": "socket recv blocks on the peer",
    "sendall": "socket sendall blocks on the peer",
    "accept": "socket accept blocks on the peer",
    "getresponse": "HTTP response read blocks on the peer",
    "urlopen": "urlopen is unbounded network I/O",
    "communicate": "subprocess communicate blocks on the child",
    "block_until_ready": "device sync stalls until the TPU drains",
}


def _bounded(call: ast.Call, timeout_position: int = 0) -> bool:
    """Does this call carry a REAL timeout? The positional slot
    matters: ``result``/``join``/``wait`` take timeout first, but
    ``Queue.get(block, timeout)`` takes it SECOND — ``get(True)`` is
    the block flag and still waits forever. Constant ``None``/bools
    never bound anything."""
    arg = None
    if len(call.args) > timeout_position:
        arg = call.args[timeout_position]
    else:
        for kw in call.keywords:
            if kw.arg == "timeout":
                arg = kw.value
    if arg is None:
        return False
    if isinstance(arg, ast.Constant) and (
            arg.value is None or isinstance(arg.value, bool)):
        return False
    return True


def classify_blocking(call: ast.Call) -> Optional[str]:
    """A one-line reason when this call blocks the calling thread,
    else None. ``.wait()`` is handled separately by lock-discipline
    (waiting on the innermost held condition is the cv idiom)."""
    func = call.func
    if isinstance(func, ast.Name):
        return _BLOCKING_NAME_CALLS.get(func.id)
    if not isinstance(func, ast.Attribute):
        return None
    receiver = func.value
    receiver_name = terminal_name(receiver)
    if receiver_name is not None:
        reason = _BLOCKING_MODULE_CALLS.get((receiver_name, func.attr))
        if reason is not None:
            return reason
    if func.attr in _BLOCKING_METHODS:
        return _BLOCKING_METHODS[func.attr]
    if func.attr == "result" and not _bounded(call):
        return "Future.result() without a timeout blocks indefinitely"
    if func.attr == "join" and not _bounded(call) and \
            receiver_name not in (None, "os", "posixpath", "ntpath",
                                  "path", "shlex"):
        # str.join / os.path.join take args, so an arg-less join on a
        # non-path receiver is a thread/process join.
        return "join() without a timeout blocks indefinitely"
    if func.attr == "get" and not _bounded(call, timeout_position=1) and \
            not _nonblocking_get(call) and \
            receiver_name is not None and "queue" in receiver_name.lower():
        return "Queue.get() without a timeout blocks indefinitely"
    return None


def _nonblocking_get(call: ast.Call) -> bool:
    """``Queue.get(False)`` / ``get(block=False)`` raises Empty
    immediately — the explicitly non-blocking form."""
    block = call.args[0] if call.args else next(
        (kw.value for kw in call.keywords if kw.arg == "block"), None)
    return isinstance(block, ast.Constant) and not block.value


def untimed_wait(call: ast.Call) -> Optional[str]:
    """Receiver text when this is ``<x>.wait(...)`` with no timeout
    (Condition.wait / Event.wait / Thread-like), else None."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "wait" and \
            not _bounded(call):
        return expr_text(func.value)
    return None
