"""status-literal and retry-after checkers.

status-literal: gRPC-status and HTTP-code literals must route through
the one canonical mapping table in ``client_tpu/status_map.py``. Before
this checker existed the same status->code tables were hand-copied
into three front-ends and drifted across ~29 call sites. Flagged
shapes (inside the scoped transport/server modules):

* a dict literal mapping two or more canonical status strings to
  HTTP ints or ``grpc.StatusCode`` members — a shadow mapping table;
* an HTTP error-code literal (400/404/409/429/500/501/503/504) used
  as a ``status=``/``code=`` keyword, as a dict value keyed by a
  canonical status string, or in an ``in (…)``/``== …`` comparison;
* any ``grpc.StatusCode.<X>`` attribute access outside status_map.

retry-after: every ``UNAVAILABLE``/``RESOURCE_EXHAUSTED`` error
construction must attach a Retry-After estimate (the
``retry_after_s`` attribute the front-ends serialize). Historical
bug: PR 7's quota rejects advertised Retry-After while queue sheds
and drain rejects sent the meaningless legacy "1". The canonical
constructor is ``status_map.retryable_error(...)``; a direct
``InferenceServerException(status="UNAVAILABLE")`` with no
``<name>.retry_after_s = …`` in the same function is an error."""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.tpulint.framework import (
    Finding,
    SourceFile,
    iter_functions,
    own_nodes,
)

#: The module that owns the mapping — everything here is allowed in it.
STATUS_MAP_MODULE = "client_tpu/status_map.py"

# The vocabulary is DERIVED from the canonical table, not copied: a
# status/code added to status_map is immediately gated here too (a
# hand-copied set already drifted once — 401/403 were mapped but
# unflagged on day one).
from client_tpu import status_map as _status_map  # noqa: E402

CANONICAL_STATUSES = frozenset(_status_map.HTTP_STATUS) | {
    "CANCELLED", "OK"}

HTTP_ERROR_CODES = frozenset(_status_map.HTTP_STATUS.values())

RETRYABLE_STATUSES = _status_map.RETRYABLE_STATUSES


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_code(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool) \
            and node.value in HTTP_ERROR_CODES:
        return node.value
    return None


def _is_status_code_attr(node: ast.AST) -> bool:
    """``grpc.StatusCode.X`` / ``StatusCode.X`` attribute chains."""
    if not isinstance(node, ast.Attribute):
        return False
    value = node.value
    if isinstance(value, ast.Attribute) and value.attr == "StatusCode":
        return True
    if isinstance(value, ast.Name) and value.id == "StatusCode":
        return True
    return False


def check_status_literals(src: SourceFile) -> List[Finding]:
    if src.rel_path == STATUS_MAP_MODULE:
        return []
    findings: List[Finding] = []

    for node in ast.walk(src.tree):
        # Shadow mapping tables: {"NOT_FOUND": 404, ...} or
        # {"NOT_FOUND": grpc.StatusCode.NOT_FOUND, ...}.
        if isinstance(node, ast.Dict):
            canonical_keys = [k for k in node.keys
                              if k is not None and
                              _const_str(k) in CANONICAL_STATUSES]
            if len(canonical_keys) >= 2:
                findings.append(src.finding(
                    "status-literal", node,
                    "shadow status mapping table — use "
                    "client_tpu/status_map.py, the one canonical table"))
                continue
        # grpc.StatusCode.* anywhere outside the canonical map.
        if _is_status_code_attr(node):
            findings.append(src.finding(
                "status-literal", node,
                "grpc.StatusCode.%s referenced directly — route through "
                "status_map.grpc_code()" % node.attr))
        # status=<error literal> keywords.
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in ("status", "code", "status_code"):
                    code = _const_code(kw.value)
                    if code is not None:
                        findings.append(src.finding(
                            "status-literal", kw.value,
                            "bare HTTP %d literal as %s= — use "
                            "status_map.http_status()" % (code, kw.arg)))
        # Comparisons against error-code literals: status in (503, 429)
        # or status == 503.
        if isinstance(node, ast.Compare):
            for comparator in node.comparators:
                elements = comparator.elts if isinstance(
                    comparator, (ast.Tuple, ast.List, ast.Set)) else \
                    [comparator]
                codes = [c for c in (
                    _const_code(e) for e in elements) if c is not None]
                if codes:
                    findings.append(src.finding(
                        "status-literal", node,
                        "comparison against bare HTTP code(s) %s — use "
                        "status_map constants (e.g. RETRYABLE_HTTP)"
                        % sorted(codes)))
    return findings


def _status_kwarg(call: ast.Call) -> Optional[str]:
    """The canonical status a constructor call carries, if literal."""
    for kw in call.keywords:
        if kw.arg == "status":
            return _const_str(kw.value)
    # InferenceServerException(msg, "UNAVAILABLE") positional form.
    if len(call.args) >= 2:
        return _const_str(call.args[1])
    return None


def check_retry_after(src: SourceFile) -> List[Finding]:
    if src.rel_path == STATUS_MAP_MODULE:
        return []
    findings: List[Finding] = []
    for _qual, _cls, func in iter_functions(src.tree):
        # Names that get a ``retry_after_s`` attribute somewhere in
        # this function (the legacy attach pattern). Pruned walk: a
        # nested helper attaching to ITS local must not excuse the
        # enclosing function's bare construction.
        attached = set()
        for node in own_nodes(func):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Attribute) and \
                            target.attr == "retry_after_s" and \
                            isinstance(target.value, ast.Name):
                        attached.add(target.value.id)
        for node in own_nodes(func):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            name = callee.attr if isinstance(callee, ast.Attribute) \
                else (callee.id if isinstance(callee, ast.Name) else "")
            if name != "InferenceServerException":
                continue
            status = _status_kwarg(node)
            if status not in RETRYABLE_STATUSES:
                continue
            if any(kw.arg == "retry_after_s" for kw in node.keywords):
                continue
            # Excused when the construction is assigned to a name that
            # later gets .retry_after_s set in this function.
            assigned_name = _assignment_target_name(func, node)
            if assigned_name is not None and assigned_name in attached:
                continue
            findings.append(src.finding(
                "retry-after", node,
                "%s error raised without a Retry-After estimate — use "
                "status_map.retryable_error(msg, status, retry_after_s)"
                % status))
    return findings


def _assignment_target_name(func: ast.AST, call: ast.Call) -> Optional[str]:
    for node in own_nodes(func):
        if isinstance(node, ast.Assign) and node.value is call:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    return target.id
    return None
