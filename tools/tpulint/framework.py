"""tpulint core: findings, suppressions, baseline, file discovery.

The repo-specific static-analysis framework (stdlib ``ast`` only — the
container carries no third-party linters). Each checker is grounded in
a defect class this repo has actually shipped and fixed; see
docs/static_analysis.md for the catalog and the historical bug behind
every checker id.

Suppression syntax (a reason is REQUIRED — a bare disable is itself a
finding)::

    something_flagged()  # tpulint: disable=lock-discipline -- probe is bounded

The comment may also stand alone on the line directly above the
flagged statement. Accepted pre-existing findings live in
``tools/tpulint/baseline.json``; the CI gate is zero NEW findings and
zero STALE baseline entries (an entry whose anchored line changed or
vanished must be pruned, so the baseline can only shrink).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

#: checker id -> one-line defect class (the catalog; docs/static_analysis.md
#: carries the long form with the motivating historical bug).
CHECKER_IDS = {
    "lock-discipline": "blocking call while a lock is held",
    "lock-order": "cyclic lock-acquisition order (static deadlock)",
    "resource-pairing": "acquire/begin_* without a release in finally/__exit__",
    "status-literal": "HTTP/gRPC status literal outside client_tpu/status_map.py",
    "retry-after": "UNAVAILABLE/RESOURCE_EXHAUSTED error without Retry-After",
    "aio-blocking": "synchronous blocking call inside async def",
    "proto-drift": ".proto / *_pb2.py / extend_inference_proto.py disagree",
    "metrics-doc-drift": "tpu_* family and docs/metrics.md disagree",
    "bad-suppression": "tpulint disable comment without a reason",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def format(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.checker,
                                   self.message)

    def key(self) -> Tuple[str, str, int]:
        return (self.checker, self.path, self.line)


class SourceFile:
    """One parsed python file plus its suppression table."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path = REPO_ROOT):
        self.abs_path = path
        self.rel_path = path.relative_to(root).as_posix()
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        self._suppressed, self.bad_suppressions = _parse_suppressions(
            self.lines, self.rel_path)

    def suppressed(self, checker: str, line: int) -> bool:
        return checker in self._suppressed.get(line, ())

    def finding(self, checker: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(checker, self.rel_path, int(line), message)


_DISABLE = re.compile(
    r"#\s*tpulint:\s*disable=(?P<ids>[a-z-]+(?:\s*,\s*[a-z-]+)*)"
    r"(?P<reason>\s+--\s+\S.*)?")


def _parse_suppressions(lines: Sequence[str], rel_path: str):
    """line number -> set of disabled checker ids. A stand-alone
    comment line applies to the next non-blank line; an inline comment
    applies to its own line. A disable without a ``-- reason`` is
    reported as a ``bad-suppression`` finding instead of honored."""
    suppressed: Dict[int, set] = {}
    bad: List[Finding] = []
    for lineno, text in enumerate(lines, 1):
        match = _DISABLE.search(text)
        if match is None:
            continue
        ids = {part.strip() for part in match.group("ids").split(",")}
        unknown = ids - set(CHECKER_IDS)
        if match.group("reason") is None:
            bad.append(Finding(
                "bad-suppression", rel_path, lineno,
                "disable=%s has no ' -- reason'; a suppression must "
                "say why the finding is accepted" % ",".join(sorted(ids))))
            continue
        if unknown:
            bad.append(Finding(
                "bad-suppression", rel_path, lineno,
                "unknown checker id(s) %s in disable comment"
                % ",".join(sorted(unknown))))
            ids -= unknown
        target = lineno
        if text.lstrip().startswith("#"):
            # Stand-alone comment: applies to the next non-blank,
            # non-comment line.
            for follow in range(lineno + 1, len(lines) + 1):
                stripped = lines[follow - 1].strip()
                if stripped and not stripped.startswith("#"):
                    target = follow
                    break
        suppressed.setdefault(target, set()).update(ids)
        # An inline disable also covers a multi-line statement that
        # STARTS on this line; checkers anchor findings at the
        # statement's first line, so same-line coverage suffices.
    return suppressed, bad


def iter_python_files(root: pathlib.Path,
                      rel_dirs: Iterable[str]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for rel in rel_dirs:
        base = root / rel
        if base.is_file():
            files.append(base)
            continue
        if base.is_dir():
            files.extend(sorted(base.rglob("*.py")))
    # Generated protobuf modules are machine-written; linting them
    # produces nothing actionable.
    return [f for f in files if not f.name.endswith("_pb2.py")]


def load_sources(root: pathlib.Path,
                 rel_dirs: Iterable[str]) -> List[SourceFile]:
    return [SourceFile(path, root)
            for path in iter_python_files(root, rel_dirs)]


# -- baseline ---------------------------------------------------------------

BASELINE_PATH = REPO_ROOT / "tools" / "tpulint" / "baseline.json"


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> List[dict]:
    if not path.exists():
        return []
    return json.loads(path.read_text())


def save_baseline(findings: Sequence[Finding], root: pathlib.Path,
                  path: pathlib.Path = BASELINE_PATH) -> None:
    entries = []
    for finding in sorted(findings, key=lambda f: (f.path, f.line,
                                                   f.checker)):
        entries.append({
            "checker": finding.checker,
            "path": finding.path,
            "line": finding.line,
            # Content anchor: the stripped source text of the flagged
            # line. If the line moves or changes, the entry goes STALE
            # and the gate fails until the baseline is pruned — stale
            # suppressions can never pile up silently.
            "text": _line_text(root, finding.path, finding.line),
            "message": finding.message,
        })
    path.write_text(json.dumps(entries, indent=1) + "\n")


def _line_text(root: pathlib.Path, rel_path: str, line: int) -> str:
    try:
        lines = (root / rel_path).read_text().splitlines()
        return lines[line - 1].strip()
    except (OSError, IndexError):
        return ""


def apply_baseline(findings: Sequence[Finding], baseline: Sequence[dict],
                   root: pathlib.Path):
    """Split findings into (new, accepted) and report stale baseline
    entries. A baseline entry matches a finding only when checker,
    path, line AND the anchored line text all still agree."""
    index = {}
    for entry in baseline:
        index[(entry["checker"], entry["path"], entry["line"])] = entry
    new: List[Finding] = []
    accepted: List[Finding] = []
    matched = set()
    for finding in findings:
        entry = index.get(finding.key())
        if entry is not None and \
                _line_text(root, finding.path, finding.line) == entry["text"]:
            accepted.append(finding)
            matched.add(finding.key())
        else:
            new.append(finding)
    stale = []
    for key, entry in index.items():
        if key in matched:
            continue
        stale.append("%s:%d: [%s] baseline entry is stale (line changed, "
                     "moved, or the finding is fixed) — prune it: %r"
                     % (entry["path"], entry["line"], entry["checker"],
                        entry["text"]))
    return new, accepted, stale


# -- shared AST helpers -----------------------------------------------------

LOCK_NAME = re.compile(
    r"(^|_)(lock|mutex|cv|cond|condition)$|(^|_)locks?$")


def expr_text(node: ast.AST) -> str:
    """Stable source-ish text for an expression (receiver matching)."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs we see
        return ast.dump(node)


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a Name/Attribute chain, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_lockish(node: ast.AST) -> bool:
    """Does this with-item / receiver look like a mutex or condition
    variable? Name-based: the repo's idiom is ``self._lock`` /
    ``self._cv`` / ``tail_lock`` etc."""
    name = terminal_name(node)
    return name is not None and LOCK_NAME.search(name) is not None


def own_nodes(node: ast.AST):
    """Descendants of ``node`` excluding nested function/lambda/class
    bodies — their statements run in a different frame (and possibly
    at a different time), so they must never color the enclosing
    scope. The one pruned-walk helper every checker shares (plain
    ``ast.walk`` + ``continue`` does NOT prune subtrees)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def iter_functions(tree: ast.AST):
    """Yield (qualname, class_name_or_None, func_node) for every
    function/method, including nested ones."""
    stack: List[Tuple[str, Optional[str], ast.AST]] = [("", None, tree)]
    while stack:
        prefix, cls, node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append(("%s%s." % (prefix, child.name), child.name,
                              child))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = "%s%s" % (prefix, child.name)
                yield qual, cls, child
                stack.append(("%s." % qual, cls, child))
