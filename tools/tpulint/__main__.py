"""CLI: ``python -m tools.tpulint [--all] [--update-baseline] [--list]``.

Default run: every static checker over the tree, gated against
``tools/tpulint/baseline.json`` — exit non-zero on any NEW finding,
any STALE baseline entry, or any disable comment without a reason.

``--all`` additionally runs the live Prometheus-exposition lint
(tools/metrics_lint.py: spins an in-process core, drives load, lints
two scrapes) so CI has exactly one static-analysis entry point.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))

from tools import tpulint  # noqa: E402
from tools.tpulint.framework import CHECKER_IDS  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="repo-specific concurrency & protocol static analysis")
    parser.add_argument(
        "--all", action="store_true",
        help="also run the live /metrics exposition lint "
             "(tools/metrics_lint.py) — the single CI entry point")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite tools/tpulint/baseline.json with the current "
             "finding set (review the diff — the baseline should only "
             "ever shrink)")
    parser.add_argument(
        "--list", action="store_true",
        help="print the checker catalog and exit")
    args = parser.parse_args(argv)

    if args.list:
        for checker_id, summary in sorted(CHECKER_IDS.items()):
            print("%-18s %s" % (checker_id, summary))
        return 0

    if args.update_baseline:
        count = tpulint.update_baseline()
        print("tpulint: baseline rewritten with %d accepted finding%s"
              % (count, "" if count == 1 else "s"))
        return 0

    new, accepted, stale = tpulint.run_gated()
    for finding in new:
        print("tpulint: %s" % finding.format(), file=sys.stderr)
    for entry in stale:
        print("tpulint: %s" % entry, file=sys.stderr)
    rc = 0
    if new or stale:
        print("tpulint FAILED: %d new finding%s, %d stale baseline "
              "entr%s (baseline: %d accepted)"
              % (len(new), "" if len(new) == 1 else "s",
                 len(stale), "y" if len(stale) == 1 else "ies",
                 len(accepted)), file=sys.stderr)
        rc = 1
    else:
        print("tpulint passed: 0 new findings (%d baselined)"
              % len(accepted))

    if args.all and rc == 0:
        # The exposition lint drives a live core; keep it after the
        # static pass so a broken tree fails fast and cheap first.
        import tools.metrics_lint as metrics_lint

        rc = metrics_lint.main()
    return rc


if __name__ == "__main__":
    sys.exit(main())
