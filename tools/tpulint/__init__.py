"""tpulint — repo-specific concurrency & protocol static analysis.

Entry point: ``python -m tools.tpulint`` (see __main__.py). The
checkers, their defect classes, and the historical bugs that motivate
them are cataloged in docs/static_analysis.md.
"""

from __future__ import annotations

import pathlib
from typing import Dict, List, Tuple

from tools.tpulint.check_aio import check_aio_blocking
from tools.tpulint.check_drift import (
    check_metrics_doc_drift,
    check_proto_drift,
)
from tools.tpulint.check_locks import check_lock_discipline, check_lock_order
from tools.tpulint.check_pairing import check_resource_pairing
from tools.tpulint.check_status import check_retry_after, check_status_literals
from tools.tpulint.framework import (
    BASELINE_PATH,
    CHECKER_IDS,
    REPO_ROOT,
    Finding,
    SourceFile,
    apply_baseline,
    iter_python_files,
    load_baseline,
    save_baseline,
)

__all__ = [
    "CHECKER_IDS",
    "Finding",
    "SourceFile",
    "run",
    "run_gated",
]

# Where each checker looks. The concurrency checkers cover the serving
# core and the perf harness (the two lock-heavy trees); the status
# checkers cover every module that translates between canonical status
# strings and wire codes; aio covers everything (it only fires inside
# ``async def``).
SCOPES: Dict[str, List[str]] = {
    "lock-discipline": ["client_tpu/server", "client_tpu/perf",
                        "client_tpu/robust.py"],
    "lock-order": ["client_tpu/server"],
    "resource-pairing": ["client_tpu/server", "client_tpu/perf"],
    "status": ["client_tpu/server", "client_tpu/http", "client_tpu/grpc",
               "client_tpu/robust.py", "client_tpu/protocol/http_wire.py",
               "client_tpu/status_map.py"],
    "retry-after": ["client_tpu/server", "client_tpu/status_map.py"],
    "aio-blocking": ["client_tpu"],
}


def run(root: pathlib.Path = REPO_ROOT) -> List[Finding]:
    """All checkers over ``root``; suppressions applied, baseline NOT
    applied (callers gate via :func:`run_gated`)."""
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}

    def load(scope_key: str) -> List[SourceFile]:
        # Scopes overlap heavily (server/ appears in five of them):
        # parse each file once and share the SourceFile.
        loaded = []
        for path in iter_python_files(root, SCOPES[scope_key]):
            rel = path.relative_to(root).as_posix()
            src = sources.get(rel)
            if src is None:
                src = SourceFile(path, root)
                sources[rel] = src
            loaded.append(src)
        return loaded

    for src in load("lock-discipline"):
        findings.extend(check_lock_discipline(src))
    findings.extend(check_lock_order(load("lock-order")))
    for src in load("resource-pairing"):
        findings.extend(check_resource_pairing(src))
    for src in load("status"):
        findings.extend(check_status_literals(src))
    for src in load("retry-after"):
        findings.extend(check_retry_after(src))
    for src in load("aio-blocking"):
        findings.extend(check_aio_blocking(src))
    findings.extend(check_proto_drift(root))
    findings.extend(check_metrics_doc_drift(root))

    # Uniform suppression pass + bad-suppression reporting.
    kept: List[Finding] = []
    seen = set()
    for finding in findings:
        src = sources.get(finding.path)
        if src is not None and src.suppressed(finding.checker, finding.line):
            continue
        if (finding.checker, finding.path, finding.line,
                finding.message) in seen:
            continue
        seen.add((finding.checker, finding.path, finding.line,
                  finding.message))
        kept.append(finding)
    for src in sources.values():
        kept.extend(src.bad_suppressions)
    kept.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    return kept


def run_gated(root: pathlib.Path = REPO_ROOT,
              baseline_path: pathlib.Path = None
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new_findings, baseline_accepted, stale_baseline_entries)."""
    findings = run(root)
    baseline = load_baseline(baseline_path or BASELINE_PATH)
    return apply_baseline(findings, baseline, root)


def update_baseline(root: pathlib.Path = REPO_ROOT,
                    baseline_path: pathlib.Path = None) -> int:
    # bad-suppression is never baselinable: accepting one would
    # permanently legitimize a reason-less disable comment, voiding
    # the "a bare disable is itself a finding" invariant. Write the
    # reason instead.
    findings = [f for f in run(root) if f.checker != "bad-suppression"]
    save_baseline(findings, root, baseline_path or BASELINE_PATH)
    return len(findings)
