"""aio-blocking checker.

Synchronous sleeps, sync sockets/subprocess I/O, unbounded
``Future.result()``/``Queue.get()``/``join()`` inside ``async def``
stall the whole event loop — in the aio clients that freezes every
in-flight request sharing the loop, and in the aiohttp front-end it
freezes the server. (The aiohttp front-end's own idiom is to push
sync core calls through ``run_in_executor``; this checker keeps it
that way.)"""

from __future__ import annotations

import ast
from typing import List

from tools.tpulint.blocking import classify_blocking, untimed_wait
from tools.tpulint.framework import (
    Finding,
    SourceFile,
    iter_functions,
    own_nodes,
)


def _own_calls(func: ast.AST):
    """Call nodes belonging to ``func`` itself — nested defs (sync
    helpers handed to executors, callbacks) run on their own thread
    and are excluded."""
    for node in own_nodes(func):
        if isinstance(node, ast.Call):
            yield node


def check_aio_blocking(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for _qual, _cls, func in iter_functions(src.tree):
        if not isinstance(func, ast.AsyncFunctionDef):
            continue
        # ``await x.wait()`` / ``await loop.run_in_executor(...)`` are
        # the non-blocking aio idiom — an awaited call never stalls
        # the loop, whatever its name.
        awaited = {id(node.value) for node in ast.walk(func)
                   if isinstance(node, ast.Await)}
        for call in _own_calls(func):
            if id(call) in awaited:
                continue
            reason = classify_blocking(call)
            if reason is not None:
                findings.append(src.finding(
                    "aio-blocking", call,
                    "%s inside async def %s — it stalls the event loop; "
                    "await the aio equivalent or push it through "
                    "run_in_executor" % (reason, func.name)))
                continue
            waited_on = untimed_wait(call)
            if waited_on is not None:
                findings.append(src.finding(
                    "aio-blocking", call,
                    "%s.wait() without a timeout inside async def %s "
                    "stalls the event loop" % (waited_on, func.name)))
    return findings
