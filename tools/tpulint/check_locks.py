"""lock-discipline and lock-order checkers.

lock-discipline: no blocking call (sleep, unbounded Future.result /
Queue.get / wait / join, socket & subprocess I/O, jax device
transfers) lexically inside a ``with <lock>:`` body or between
explicit ``acquire()``/``release()`` calls. Waiting without a timeout
on the innermost held condition variable is the cv idiom and allowed;
waiting on anything else while a lock is held is not. (Historical bug:
PR 6 rendered trace records under ``_trace_lock``.)

lock-order: builds the inter-procedural lock-acquisition graph (which
locks are taken while which are held, resolved through ``self._x``
attributes and module-local calls) and fails on cycles — a static
deadlock detector for the batcher/replica/cache/repository lock web.
Also flags re-acquisition of a known non-reentrant lock through a
self-call chain."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.tpulint.blocking import classify_blocking, untimed_wait
from tools.tpulint.framework import (
    Finding,
    SourceFile,
    expr_text,
    is_lockish,
    iter_functions,
    own_nodes,
    terminal_name,
)

# -- lock-discipline --------------------------------------------------------


def _calls_in(node: ast.AST):
    """Call nodes inside ``node``, not descending into nested function
    definitions (they run later, outside the lexical lock region)."""
    for child in own_nodes(node):
        if isinstance(child, ast.Call):
            yield child


def _releases_in(stmts: List[ast.stmt]) -> List[str]:
    """Lock texts released anywhere in these statements (pruned)."""
    released = []
    for stmt in stmts:
        for call in _calls_in(stmt):
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "release" and \
                    is_lockish(call.func.value):
                released.append(expr_text(call.func.value))
    return released


def _lock_call(stmt: ast.stmt, attr: str) -> Optional[str]:
    """Lock text when ``stmt`` is ``<lock>.acquire()``/``.release()``
    (bare expression or assignment of the acquire result)."""
    value = None
    if isinstance(stmt, ast.Expr):
        value = stmt.value
    elif isinstance(stmt, ast.Assign):
        value = stmt.value
    if isinstance(value, ast.Call) and \
            isinstance(value.func, ast.Attribute) and \
            value.func.attr == attr and is_lockish(value.func.value):
        return expr_text(value.func.value)
    return None


def check_lock_discipline(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []

    def flag(call: ast.Call, held: List[str], reason: str) -> None:
        findings.append(src.finding(
            "lock-discipline", call,
            "%s while holding %s" % (reason, held[-1])))

    def scan_expr(node: ast.AST, held: List[str]) -> None:
        if not held:
            return
        for call in _calls_in(node):
            waited_on = untimed_wait(call)
            if waited_on is not None:
                # cv.wait() releases cv's own lock — fine when cv IS
                # the only lock held; a deadlock when an outer lock
                # stays held across the wait.
                if waited_on == held[-1] and len(held) == 1:
                    continue
                outer = [h for h in held if h != waited_on]
                flag(call, outer or held,
                     "%s.wait() without a timeout" % waited_on)
                continue
            reason = classify_blocking(call)
            if reason is not None:
                flag(call, held, reason)

    def visit_block(stmts: List[ast.stmt], held: List[str]) -> None:
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new = []
                for item in stmt.items:
                    scan_expr(item.context_expr, held)
                    if is_lockish(item.context_expr):
                        new.append(expr_text(item.context_expr))
                visit_block(stmt.body, held + new)
                continue
            acquired = _lock_call(stmt, "acquire")
            if acquired is not None:
                held.append(acquired)
                continue
            released = _lock_call(stmt, "release")
            if released is not None and released in held:
                held.remove(released)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                scan_expr(stmt.test, held)
                visit_block(stmt.body, held)
                visit_block(stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan_expr(stmt.iter, held)
                visit_block(stmt.body, held)
                visit_block(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                visit_block(stmt.body, held)
                for handler in stmt.handlers:
                    visit_block(handler.body, held)
                visit_block(stmt.orelse, held)
                visit_block(stmt.finalbody, held)
                # A release in the finalbody ALWAYS runs: the lock is
                # no longer held after the Try (the canonical
                # acquire/try/finally/release idiom must not taint the
                # rest of the block).
                for released in _releases_in(stmt.finalbody):
                    if released in held:
                        held.remove(released)
            else:
                scan_expr(stmt, held)

    for _qual, _cls, func in iter_functions(src.tree):
        visit_block(func.body, [])

    return findings


# -- lock-order -------------------------------------------------------------


class _FuncLockInfo:
    def __init__(self, qual: str):
        self.qual = qual
        self.direct: Set[str] = set()        # locks acquired anywhere
        # (held_locks_tuple, "lock"|"call", lock_name_or_callee, path, line)
        self.events: List[Tuple[Tuple[str, ...], str, str, str, int]] = []


def _collect_lock_kinds(src: SourceFile, module: str):
    """{class: {attr: kind}} and {class: {attr: aliased_attr}} from
    ``self.X = threading.Lock()/RLock()/Condition(self.Y)`` inits."""
    kinds: Dict[str, Dict[str, str]] = {}
    aliases: Dict[str, Dict[str, str]] = {}
    for _qual, cls, func in iter_functions(src.tree):
        if cls is None:
            continue
        for stmt in ast.walk(func):
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call):
                continue
            ctor = terminal_name(stmt.value.func)
            if ctor not in ("Lock", "RLock", "Condition", "Semaphore",
                            "BoundedSemaphore"):
                continue
            for target in stmt.targets:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    kinds.setdefault(cls, {})[target.attr] = ctor
                    if ctor == "Condition" and stmt.value.args:
                        wrapped = stmt.value.args[0]
                        if isinstance(wrapped, ast.Attribute) and \
                                isinstance(wrapped.value, ast.Name) and \
                                wrapped.value.id == "self":
                            aliases.setdefault(cls, {})[target.attr] = \
                                wrapped.attr
    return kinds, aliases


def _canonical(node: ast.AST, module: str, cls: Optional[str],
               aliases: Dict[str, Dict[str, str]]) -> str:
    """Stable identity for a lock expression. ``self._x`` resolves to
    ``module.Class._x`` (a Condition wrapping another lock resolves to
    the wrapped lock — same underlying mutex, not an ordering edge)."""
    text = expr_text(node)
    if cls is not None and text.startswith("self."):
        attr = text[len("self."):]
        resolved = aliases.get(cls, {}).get(attr, attr)
        return "%s.%s.%s" % (module, cls, resolved)
    return "%s:%s" % (module, text)


def check_lock_order(sources: List[SourceFile]) -> List[Finding]:
    infos: Dict[str, _FuncLockInfo] = {}
    per_module_funcs: Dict[str, Set[str]] = {}
    per_class_methods: Dict[Tuple[str, str], Set[str]] = {}
    kinds_by_class: Dict[Tuple[str, str], Dict[str, str]] = {}

    prepared = []
    for src in sources:
        module = src.rel_path[:-3].replace("/", ".")
        kinds, aliases = _collect_lock_kinds(src, module)
        for cls, attrs in kinds.items():
            kinds_by_class[(module, cls)] = attrs
        names = {qual for qual, _cls, _f in iter_functions(src.tree)}
        per_module_funcs[module] = {n for n in names if "." not in n}
        for qual in names:
            if "." in qual:
                cls, _, meth = qual.rpartition(".")
                if "." not in cls:
                    per_class_methods.setdefault((module, cls),
                                                 set()).add(meth)
        prepared.append((src, module, aliases))

    def resolve_call(call: ast.Call, module: str,
                     cls: Optional[str]) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id == "self" and cls is not None and \
                func.attr in per_class_methods.get((module, cls), ()):
            return "%s.%s.%s" % (module, cls, func.attr)
        if isinstance(func, ast.Name) and \
                func.id in per_module_funcs.get(module, ()):
            return "%s.%s" % (module, func.id)
        return None

    for src, module, aliases in prepared:
        for qual, cls, func in iter_functions(src.tree):
            info = _FuncLockInfo("%s.%s" % (module, qual))
            infos[info.qual] = info

            def visit(stmts: List[ast.stmt], held: Tuple[str, ...],
                      info=info, cls=cls, src=src, module=module) -> None:
                for stmt in stmts:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                        continue
                    if isinstance(stmt, (ast.With, ast.AsyncWith)):
                        new = list(held)
                        for item in stmt.items:
                            if is_lockish(item.context_expr):
                                lock = _canonical(item.context_expr,
                                                  module, cls,
                                                  {cls: aliases.get(cls, {})}
                                                  if cls else {})
                                info.direct.add(lock)
                                info.events.append(
                                    (tuple(new), "lock", lock,
                                     src.rel_path, item.context_expr.lineno))
                                new.append(lock)
                        visit(stmt.body, tuple(new))
                        continue
                    for call in _calls_in(stmt):
                        func_node = call.func
                        if isinstance(func_node, ast.Attribute) and \
                                func_node.attr == "acquire" and \
                                is_lockish(func_node.value):
                            lock = _canonical(func_node.value, module, cls,
                                              {cls: aliases.get(cls, {})}
                                              if cls else {})
                            info.direct.add(lock)
                            info.events.append(
                                (held, "lock", lock, src.rel_path,
                                 call.lineno))
                            continue
                        callee = resolve_call(call, module, cls)
                        if callee is not None:
                            info.events.append(
                                (held, "call", callee, src.rel_path,
                                 call.lineno))
                    if isinstance(stmt, (ast.If, ast.While)):
                        visit(stmt.body, held)
                        visit(stmt.orelse, held)
                    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                        visit(stmt.body, held)
                        visit(stmt.orelse, held)
                    elif isinstance(stmt, ast.Try):
                        visit(stmt.body, held)
                        for handler in stmt.handlers:
                            visit(handler.body, held)
                        visit(stmt.orelse, held)
                        visit(stmt.finalbody, held)

            visit(func.body, ())

    # Fixpoint: the transitive lock set each function may acquire.
    acquires: Dict[str, Set[str]] = {
        qual: set(info.direct) for qual, info in infos.items()}
    changed = True
    while changed:
        changed = False
        for qual, info in infos.items():
            for _held, kind, target, _path, _line in info.events:
                if kind == "call" and target in acquires:
                    before = len(acquires[qual])
                    acquires[qual] |= acquires[target]
                    changed = changed or len(acquires[qual]) != before

    # Edge set: held -> acquired (with a representative location).
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    reentrant: List[Finding] = []
    for qual, info in infos.items():
        for held, kind, target, path, line in info.events:
            acquired = {target} if kind == "lock" else \
                acquires.get(target, set())
            for h in held:
                for lock in acquired:
                    if lock == h:
                        if kind == "call" and _non_reentrant(
                                h, kinds_by_class):
                            reentrant.append(Finding(
                                "lock-order", path, line,
                                "call into %s re-acquires non-reentrant "
                                "%s already held here" % (target, h)))
                        continue
                    edges.setdefault((h, lock),
                                     (path, line, qual))

    findings = list(reentrant)
    for cycle in _find_cycles({pair for pair in edges}):
        members = set(cycle)
        in_cycle = sorted(
            (pair, loc) for pair, loc in edges.items()
            if pair[0] in members and pair[1] in members)
        (held, acquired), (path, line, qual) = in_cycle[0]
        findings.append(Finding(
            "lock-order", path, line,
            "lock-order cycle (potential deadlock) among {%s}: e.g. %s "
            "is taken while %s is held, in %s"
            % (", ".join(cycle), acquired, held, qual)))
    return findings


def _non_reentrant(lock: str, kinds_by_class) -> bool:
    parts = lock.rsplit(".", 1)
    if len(parts) != 2:
        return False
    prefix, attr = parts
    module, _, cls = prefix.rpartition(".")
    kind = kinds_by_class.get((module, cls), {}).get(attr)
    return kind in ("Lock", "Condition")


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles via SCC decomposition: each non-trivial SCC is
    reported once as a sorted node list (stable across runs so the
    baseline can anchor it)."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(node: str) -> None:
        work = [(node, iter(sorted(graph[node])))]
        index[node] = low[node] = counter[0]
        counter[0] += 1
        stack.append(node)
        on_stack.add(node)
        while work:
            current, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = low[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(sorted(graph[succ]))))
                    advanced = True
                    break
                if succ in on_stack:
                    low[current] = min(low[current], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[current])
            if low[current] == index[current]:
                component = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    component.append(member)
                    if member == current:
                        break
                if len(component) > 1:
                    sccs.append(sorted(component))

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)
    return sccs
