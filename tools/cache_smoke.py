"""Response-cache smoke gate for tools/ci_check.sh.

Runs the bench harness's hot-set replay measurement
(client_tpu.perf.bench_child.run_cache_measure) against an in-process
core serving ``simple_cache`` (the `simple` add/sub model with
response_cache.enable + a dynamic batcher) and gates on:

* the replayed hot set reaches a 100% hit ratio,
* hit-path p50 is well under miss-path p50 (< 1/2), and
* a concurrent identical-request burst executes the model exactly
  once (single-flight deduplication).

Usage: JAX_PLATFORMS=cpu python tools/cache_smoke.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main() -> int:
    from client_tpu.server.app import build_core
    from client_tpu.perf.bench_child import run_cache_measure

    core = build_core(["simple_cache"], warmup=False)
    try:
        result = run_cache_measure(core, warm_s=1.5, unique=512)
    finally:
        core.shutdown()
    print(json.dumps(result, indent=1))

    failures = []
    if result.get("warm_hit_ratio") != 1.0:
        failures.append("replayed hot set did not reach 100%% hit ratio "
                        "(got %s)" % result.get("warm_hit_ratio"))
    hit_p50 = result.get("warm_hit_p50_us", 0.0)
    miss_p50 = result.get("cold_miss_p50_us", 0.0)
    if not (0 < hit_p50 * 2 < miss_p50):
        failures.append("hit-path p50 (%.0f us) is not well under "
                        "miss-path p50 (%.0f us)" % (hit_p50, miss_p50))
    if result.get("singleflight_executions") != 1:
        failures.append("identical-request burst executed the model %s "
                        "times (single-flight wants exactly 1)"
                        % result.get("singleflight_executions"))
    for failure in failures:
        print("FAIL: %s" % failure, file=sys.stderr)
    if failures:
        return 1
    print("cache smoke passed: %.1f%% hit ratio, hit p50 %.0f us vs "
          "miss p50 %.0f us (%.1fx tput), single-flight 1 execution"
          % (result.get("warm_hit_ratio", 0.0) * 100.0, hit_p50,
             miss_p50, result.get("warm_vs_cold_speedup", 0.0)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
