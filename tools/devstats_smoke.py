#!/usr/bin/env python
"""CI smoke for the device-axis observability layer
(client_tpu/server/devstats.py, docs/device_observability.md).

Drives mixed load — dense batcher traffic, an LLM with a paged KV
pool, and a TPU-arena region — then gates:

1. **Ledger-sum tolerance** — the ``tpu_hbm_model_bytes`` rows
   (residual included) sum to within 10% of ``tpu_hbm_used_bytes``
   when the runtime reports used bytes; on the CPU dryrun (no
   ``memory_stats()``) the attributed rows themselves are the gate:
   the KV pool and arena rows must be present and match the ledger's
   internal accounting.
2. **Busy-time monotonicity** — ``tpu_device_busy_us_total`` advances
   between two scrapes with traffic in between and never decreases.
3. **Compile telemetry** — at least one XLA compile recorded per
   fresh jit-backed model (batcher bucket + LLM kernels).
4. **Profiler capture** — ``GET /v2/debug/profile`` (embedded
   front-end) returns a chrome trace that loads as strict JSON with
   at least one event from the traffic driven during the window.
5. **Overhead** — the always-on recording layer costs < 2% throughput
   (paired interleaved A/B medians on ``add_sub_large``, the shared
   ``_overhead_ab_measure`` driver telemetry and flight use).

Exits non-zero with one line per violation.
"""

from __future__ import annotations

import json
import os
import sys
import threading

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILURES: list = []


def gate(ok: bool, label: str, detail: str = "") -> None:
    line = "%s%s" % (label, (": " + detail) if detail else "")
    if ok:
        print("  ok   %s" % line)
    else:
        print("  FAIL %s" % line)
        FAILURES.append(line)


def _simple_request(model_name: str, seed: int = 0):
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    shape = [1, 16]
    a = np.full(shape, seed % 97, dtype=np.int32)
    b = np.arange(16, dtype=np.int32).reshape(shape)
    t0 = InferInput("INPUT0", shape, "INT32")
    t0.set_data_from_numpy(a)
    t1 = InferInput("INPUT1", shape, "INT32")
    t1.set_data_from_numpy(b)
    return get_inference_request(model_name=model_name,
                                 inputs=[t0, t1], outputs=None)


def _drive_dense(core, n: int = 16, threads: int = 4,
                 seed_base: int = 0) -> None:
    # seed_base keeps successive drives on DISTINCT request bytes —
    # simple_cache caches responses, and a replayed seed space would
    # serve hits without executing (no busy time to observe).
    def worker(offset: int):
        for index in range(n):
            core.infer(_simple_request(
                "simple_cache", seed_base + offset * 1000 + index))

    pool = [threading.Thread(target=worker, args=(i,))
            for i in range(threads)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()


def _drive_llm(model, prompts=("the quick brown fox", "hello")) -> int:
    import numpy as np

    tokens = 0
    for prompt in prompts:
        for _ in model.infer_stream({
            "text_input": np.array([prompt.encode()], dtype=np.object_),
            "max_tokens": np.array([3], dtype=np.int32),
        }):
            tokens += 1
    return tokens


def _parse_family(text: str, family: str):
    rows = {}
    for line in text.splitlines():
        if line.startswith(family) and not line.startswith("#"):
            name_labels, value = line.rsplit(" ", 1)
            rows[name_labels[len(family):]] = float(value)
    return rows


def main() -> int:
    from client_tpu.models.llm import LlmModel
    from client_tpu.perf.bench_child import _overhead_ab_measure
    from client_tpu.server import devstats as devstats_mod
    from client_tpu.server.app import build_core
    from client_tpu.server.http_embed import http_call

    stats = devstats_mod.get()
    print("devstats smoke: compile-listener mode = %s"
          % devstats_mod.listener_mode())
    core = build_core(["simple_cache", "add_sub_large"])
    llm = LlmModel(name="llm_smoke_devstats", decode_lanes=2,
                   kv_pages=16)
    core.repository.add_model(llm)
    try:
        # -- mixed load: dense + llm + arena --------------------------
        print("driving mixed load (dense + llm + arena)...")
        _drive_dense(core)
        tokens = _drive_llm(llm)
        gate(tokens > 0, "llm produced tokens", "%d" % tokens)
        region_id = None
        arena = core.memory.arena
        if arena is not None:
            handle = arena.create_region(1 << 16, 0)
            region_id = json.loads(handle)["region_id"]

        # -- gate 1: ledger-sum tolerance -----------------------------
        text = core.metrics_text()
        model_rows = _parse_family(text, "tpu_hbm_model_bytes")
        used_rows = _parse_family(text, "tpu_hbm_used_bytes")
        ledger_sum = sum(model_rows.values())
        if used_rows:
            used = sum(used_rows.values())
            gate(abs(ledger_sum - used) <= 0.10 * used + 1,
                 "ledger rows sum to tpu_hbm_used_bytes within 10%",
                 "ledger %d vs used %d" % (ledger_sum, used))
        else:
            # CPU dryrun: no used-bytes gauge — the attributed rows
            # themselves are the gate.
            kv = [v for k, v in model_rows.items()
                  if 'component="kv_pages"' in k]
            arena_rows = [v for k, v in model_rows.items()
                          if 'model="arena"' in k]
            gate(bool(kv) and kv[0] > 0,
                 "kv_pages ledger row present (no memory_stats "
                 "backend)", str(kv))
            gate(arena is None or (bool(arena_rows)
                                   and arena_rows[0] >= (1 << 16)),
                 "arena regions ledger row present", str(arena_rows))
            gate(abs(ledger_sum - stats.ledger.total()) < 1,
                 "exposition matches ledger accounting",
                 "%d vs %d" % (ledger_sum, stats.ledger.total()))
        if region_id is not None:
            arena.destroy_region(region_id)

        # -- gate 2: busy monotonic across two scrapes ----------------
        busy_first = _parse_family(core.metrics_text(),
                                   "tpu_device_busy_us_total")
        _drive_dense(core, n=8, threads=2, seed_base=50_000)
        busy_second = _parse_family(core.metrics_text(),
                                    "tpu_device_busy_us_total")
        gate(bool(busy_first),
             "busy-time counter present", str(busy_first))
        gate(sum(busy_second.values()) > sum(busy_first.values()),
             "busy-time counter advanced under load",
             "%d -> %d" % (sum(busy_first.values()),
                           sum(busy_second.values())))
        gate(all(busy_second.get(key, 0) >= value
                 for key, value in busy_first.items()),
             "busy-time counter monotonic per device")

        # -- gate 3: >=1 compile per fresh model ----------------------
        compiles = stats.compile_snapshot()
        for name in ("simple_cache", "llm_smoke_devstats"):
            entry = compiles.get(name, {"count": 0})
            gate(entry["count"] >= 1,
                 "compile recorded for fresh model %s" % name,
                 "count=%d" % entry["count"])

        # -- gate 4: profile endpoint returns a loadable trace --------
        stop = threading.Event()

        def traffic():
            seed = 0
            while not stop.is_set():
                seed += 1
                core.infer(_simple_request("simple_cache", seed))

        thread = threading.Thread(target=traffic, daemon=True)
        thread.start()
        try:
            status, _headers, body = http_call(
                core, "GET", "/v2/debug/profile?duration_ms=300",
                {}, b"")
        finally:
            stop.set()
            thread.join(timeout=10)
        gate(status == 200, "profile endpoint answered",
             "status %d" % status)
        doc = json.loads(body)
        gate(doc.get("duration_ms") == 300, "duration honored",
             str(doc.get("duration_ms")))
        chrome = doc.get("chrome_trace")
        events = []
        try:
            with open(chrome) as f:
                events = json.load(f)
            loadable = isinstance(events, list)
        except Exception as e:  # noqa: BLE001 — the gate reports it
            loadable = False
            print("  (chrome trace load error: %s)" % e)
        gate(loadable, "chrome trace loads as strict JSON", chrome)
        gate(doc.get("requests_captured", 0) >= 1
             and any(e.get("ph") == "X" for e in events),
             "capture window tapped live requests",
             "requests=%s events=%d"
             % (doc.get("requests_captured"), len(events)))

        # -- gate 5: paired-A/B overhead < 2% -------------------------
        # One retry with more interleaved pairs, same as the telemetry
        # and flight smokes: the true cost is microseconds against a
        # ~15 ms request, and a transient burst from another process
        # can skew a short median past 2% when the real cost is ~0.
        print("overhead A/B (paired medians on add_sub_large)...")
        result = _overhead_ab_measure(core, stats, "devstats")
        if not result["overhead_ok"]:
            print("overhead first pass %.2f%% over the gate; "
                  "re-measuring with more pairs"
                  % result["overhead_pct"])
            result = _overhead_ab_measure(core, stats, "devstats",
                                          rounds=12)
        gate(result["overhead_ok"],
             "devstats recording overhead < 2%%",
             "%.2f%% (pairs: %s)" % (result["overhead_pct"],
                                     result["pair_overheads_pct"]))
    finally:
        core.shutdown()

    if FAILURES:
        print("devstats smoke FAILED (%d gate%s):"
              % (len(FAILURES), "s" if len(FAILURES) != 1 else ""))
        for line in FAILURES:
            print("  - %s" % line)
        return 1
    print("devstats smoke passed")
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # Hard exit: the profiler gate may leave tensorflow's profiler
    # machinery mid-import/teardown, whose atexit hooks can segfault
    # AFTER the verdict is printed — the exit code must be the gates',
    # not the interpreter teardown's.
    os._exit(rc)
