#!/usr/bin/env python
"""Mid-round TPU self-measurement: the same stages the end-of-round
bench runs, invocable cheaply at any time.

Thin wrapper over ``client_tpu.perf.bench_child`` (the single source of
truth for stage definitions, watchdogs, and honest-degradation rules) —
this script only builds the native harness, computes a deadline, runs
the child on the image's default platform, and pretty-prints the
per-stage record.  Results land in ``--out`` (default
``/tmp/measure_tpu.json``) in exactly the schema ``bench.py`` emits
under ``"stages"``, so a mid-round record can be compared field-by-field
with the driver's ``BENCH_r*.json``.

Usage:
    python tools/measure_tpu.py                    # all stages, 20 min
    python tools/measure_tpu.py --budget 600       # quick pass
    python tools/measure_tpu.py --skip-stages simple_grpc,simple_inprocess
"""

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=1200.0,
                    help="wall-clock budget in seconds (default 1200)")
    ap.add_argument("--out", default="/tmp/measure_tpu.json")
    ap.add_argument("--skip-stages", default="",
                    help="comma-separated stage names to skip")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (default: image default, "
                         "i.e. TPU when the relay is up)")
    ap.add_argument("--skip-build", action="store_true",
                    help="reuse the existing native harness binary")
    args = ap.parse_args()

    t0 = time.time()
    sys.path.insert(0, str(REPO))
    import bench

    if not args.skip_build:
        bench.build_native_harness(deadline_s=min(300.0, args.budget * 0.3))

    # bench.run_child owns the init-marker watchdog (a wedged relay can
    # hang jax init forever — the child's own deadline checks only run
    # after init), the SIGINT partial-flush, and the CPU env knobs that
    # must be set before the interpreter starts.
    result = bench.run_child(
        args.platform, init_deadline_s=max(60.0, args.budget * 0.6),
        deadline_ts=t0 + args.budget,
        skip_stages=sorted(filter(None, args.skip_stages.split(","))))
    if result is None:
        print("no result — child missed init deadline or died",
              file=sys.stderr)
        sys.exit(1)
    pathlib.Path(args.out).write_text(json.dumps(result, indent=2))
    print(json.dumps(result, indent=2))
    print("\nplatform=%s harness=%s probe=%s wall=%.0fs -> %s"
          % (result.get("platform"), result.get("harness"),
             result.get("device_probe"), time.time() - t0, args.out),
          file=sys.stderr)
    sys.exit(0 if result.get("stages") else 1)


if __name__ == "__main__":
    main()
