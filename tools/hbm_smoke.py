#!/usr/bin/env python
"""CI smoke for the HBM allocator (client_tpu/server/hbm.py,
docs/hbm.md).

Serves 3x more pageable models than fit a simulated HBM budget
(``CLIENT_TPU_HBM_BUDGET``, set before jax imports) and drives a
hot-set workload: two models take continuous traffic while the cold
tail is cycled through admission-miss restores, each restore evicting
the coldest resident weights. Gates:

1. **Hot set untouched** — zero evictions of hot-model components
   across the whole churn (the admission-path ``touch_model`` heat
   signal must protect them), and no hot request fails.
2. **Hot p99 unaffected** — hot-model p99 during cold churn within
   5x the quiet-phase p99 (floor 50 ms for CI noise): restores
   serialize on the arbitration mutex, not on the serving path.
3. **Cold-start bound** — every cold model's first-request-to-served
   wall time within 10x the allocator's own restore estimate (floor
   3 s): the advertised Retry-After must be honest.
4. **Residual ~0** — after unloading everything, allocator leased
   bytes and ledger attribution are both zero: page-out/restore churn
   leaks nothing.
5. **Parity** — every response equals the model's golden (weights
   that moved host->device->host stay bit-identical).

Exits non-zero with one line per violation.
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DIM = 128
WEIGHT_BYTES = DIM * DIM * 4  # fp32
N_MODELS = 9
# 3 of 9 fit — 3x oversubscription by model count. The fit count must
# exceed the hot set by one: the two hot models pin their slots while
# the cold tail rotates through the remaining slot; a budget that
# cannot hold hot+1 would make hot evictions load-bearing instead of
# a bug.
BUDGET = int(WEIGHT_BYTES * 3.5)
HOT = ("hbm_hot_0", "hbm_hot_1")
COLD = tuple("hbm_cold_%d" % i for i in range(N_MODELS - len(HOT)))

# Must precede any jax/client_tpu import: the allocator discovers its
# budget from the environment at first device touch.
os.environ["CLIENT_TPU_HBM_BUDGET"] = str(BUDGET)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FAILURES: list = []


def gate(ok: bool, label: str, detail: str = "") -> None:
    line = "%s%s" % (label, (": " + detail) if detail else "")
    if ok:
        print("  ok   %s" % line)
    else:
        print("  FAIL %s" % line)
        FAILURES.append(line)


def _build_model(name: str, seed: int):
    import jax.numpy as jnp
    import numpy as np

    from client_tpu.server.model import ServedModel, TensorSpec

    class PagedMatmul(ServedModel):
        """OUTPUT0 = INPUT0 @ W with a per-model deterministic W —
        the smallest model whose weights are worth paging."""

        platform = "jax"

        def __init__(self):
            super().__init__()
            self.name = name
            self.pageable_weights = True
            self.max_batch_size = 0
            self.inputs = [TensorSpec("INPUT0", "FP32", [DIM])]
            self.outputs = [TensorSpec("OUTPUT0", "FP32", [DIM])]
            rows = np.arange(DIM, dtype=np.float32)
            self._w = jnp.asarray(
                np.outer(rows, rows) * 1e-4 + np.eye(DIM) * (seed + 1),
                dtype=jnp.float32)

        def infer(self, inputs, parameters=None):
            x = np.asarray(inputs["INPUT0"], dtype=np.float32)
            w = np.asarray(self._w, dtype=np.float32)
            return {"OUTPUT0": x @ w}

        def weight_state(self):
            return {"w": self._w}

        def set_weight_state(self, state):
            self._w = state["w"]

    return PagedMatmul()


def _request(name: str, seed: int = 0):
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    x = np.linspace(0.0, 1.0, DIM).astype(np.float32) + (seed % 17)
    tensor = InferInput("INPUT0", [DIM], "FP32")
    tensor.set_data_from_numpy(x)
    return get_inference_request(model_name=name, inputs=[tensor],
                                 outputs=None)


def _infer_until_served(core, name: str, deadline_s: float = 30.0):
    """Drives one request through the cold-start contract: 503 +
    Retry-After -> sleep the advised value -> retry. Returns
    (response, wall_s, saw_cold)."""
    from client_tpu.utils import InferenceServerException

    started = time.monotonic()
    saw_cold = False
    while True:
        try:
            response = core.infer(_request(name))
            return response, time.monotonic() - started, saw_cold
        except InferenceServerException as e:
            if time.monotonic() - started > deadline_s:
                raise
            saw_cold = True
            time.sleep(min(getattr(e, "retry_after_s", 0.1) or 0.1,
                           0.25))


def _percentile(values, q):
    ordered = sorted(values)
    return ordered[min(int(len(ordered) * q), len(ordered) - 1)]


def main() -> int:
    from client_tpu.server import hbm as hbm_mod
    from client_tpu.server.app import build_core

    core = build_core([], warmup=False)
    names = list(HOT) + list(COLD)
    goldens = {}
    try:
        print("hbm smoke: budget=%d bytes, %d models x %d bytes "
              "weights (%.1fx oversubscribed)"
              % (BUDGET, N_MODELS, WEIGHT_BYTES,
                 N_MODELS * WEIGHT_BYTES / float(BUDGET)))
        for seed, name in enumerate(names):
            core.repository.add_factory(
                name, lambda name=name, seed=seed: _build_model(
                    name, seed))
            core.load_model(name, warmup=False)
        snap = core.hbm.debug_snapshot()
        (dev,) = snap["devices"].values()
        gate(dev["capacity_bytes"] == BUDGET, "budget discovered",
             "capacity=%s" % dev["capacity_bytes"])
        gate(dev["leased_bytes"] <= BUDGET,
             "oversubscribed load rebalanced under budget",
             "leased=%d paged_out=%s" % (dev["leased_bytes"],
                                         snap["paged_out"]))

        # Take goldens everywhere — cold tail first, hot set LAST, so
        # the hot weights are resident (and hottest) when the quiet
        # phase starts; each arrival here may itself be a cold-start
        # restore, since the load sweep paged out the early models.
        for name in list(COLD) + list(HOT):
            response, _, _ = _infer_until_served(core, name)
            goldens[name] = list(response.raw_output_contents)

        # Prime the hot set's heat: right after the warm sweep every
        # lease sits in the same recency bucket with one touch each,
        # so the sweep's last restores may have paged a hot model —
        # serve through any cold start, then build the touch-rate
        # signal the eviction policy protects.
        for name in HOT:
            _infer_until_served(core, name)
        for index in range(50):
            for name in HOT:
                core.infer(_request(name, index))

        # Quiet phase: hot-set p99 with no churn.
        quiet_lat = []
        for index in range(150):
            for name in HOT:
                t0 = time.monotonic()
                core.infer(_request(name, index))
                quiet_lat.append(time.monotonic() - t0)
        quiet_p99 = _percentile(quiet_lat, 0.99)

        # Churn phase: the cold tail cycles through admission-miss
        # restores (each evicting the coldest resident weights) while
        # the hot set keeps serving. The eviction gate below is
        # windowed from here: the load sweep legitimately paged out
        # the then-idle hot models, the workload must not.
        evictions_before = {
            (row["model"], row["component"], row["reason"]):
                row["count"]
            for row in core.hbm.debug_snapshot()["evictions"]}
        stop = threading.Event()
        cold_walls = []
        churn_errors = []

        def churn():
            try:
                for cycle in range(3):
                    for name in COLD:
                        response, wall, saw_cold = _infer_until_served(
                            core, name)
                        cold_walls.append((name, wall, saw_cold))
                        if list(response.raw_output_contents) != \
                                goldens[name]:
                            churn_errors.append(
                                "%s parity lost after restore" % name)
            except Exception as e:  # noqa: BLE001
                churn_errors.append("churn failed: %r" % e)
            finally:
                stop.set()

        churn_thread = threading.Thread(target=churn, daemon=True)
        churn_thread.start()
        churn_lat = []
        hot_errors = 0
        index = 0
        while not stop.is_set():
            for name in HOT:
                t0 = time.monotonic()
                try:
                    # Seed 0 matches the golden request: every churn-
                    # phase response is parity-checked against it.
                    response = core.infer(_request(name, 0))
                    churn_lat.append(time.monotonic() - t0)
                    if list(response.raw_output_contents) != \
                            goldens[name]:
                        hot_errors += 1
                except Exception:  # noqa: BLE001
                    hot_errors += 1
            index += 1
        churn_thread.join(timeout=60)
        churn_p99 = _percentile(churn_lat, 0.99)

        gate(not churn_errors, "cold tail served through churn",
             "; ".join(churn_errors[:3]))
        gate(hot_errors == 0,
             "hot set never failed or lost parity during churn",
             "%d bad responses" % hot_errors)
        restores = sum(1 for _, _, cold in cold_walls if cold)
        gate(restores > 0, "churn actually exercised cold restores",
             "%d of %d cold arrivals were misses" % (restores,
                                                     len(cold_walls)))

        # Gate 1: the heat signal protected the hot set.
        snap = core.hbm.debug_snapshot()
        deltas = {}
        for row in snap["evictions"]:
            key = (row["model"], row["component"], row["reason"])
            delta = row["count"] - evictions_before.get(key, 0)
            if delta:
                deltas[key] = delta
        hot_evictions = {key: count for key, count in deltas.items()
                         if key[0] in HOT}
        total_evictions = sum(deltas.values())
        gate(total_evictions > 0 and not hot_evictions,
             "zero evictions of hot components during churn",
             "total=%d hot=%s" % (total_evictions, hot_evictions))

        # Gate 2: hot p99 unaffected by the cold churn.
        bound = max(0.050, 5.0 * quiet_p99)
        gate(churn_p99 <= bound,
             "hot p99 unaffected by churn",
             "quiet=%.1fms churn=%.1fms bound=%.1fms"
             % (quiet_p99 * 1e3, churn_p99 * 1e3, bound * 1e3))

        # Gate 3: cold-start wall time within the advertised
        # restore-bandwidth bound.
        estimate = core.hbm.restore_estimate_s(WEIGHT_BYTES)
        cold_bound = max(3.0, 10.0 * estimate)
        worst = max(wall for _, wall, _ in cold_walls)
        gate(worst <= cold_bound,
             "cold first-request latency within restore bound",
             "worst=%.3fs bound=%.3fs (estimate=%.3fs)"
             % (worst, cold_bound, estimate))

        # The exposition families saw the traffic.
        metrics = core.metrics_text()
        gate("tpu_weight_pageout_total" in metrics
             and "tpu_hbm_evictions_total" in metrics
             and "tpu_hbm_free_bytes" in metrics
             and "tpu_weight_restore_us" in metrics,
             "allocator metric families rendered")

        # Gate 4: churn leaks nothing.
        for name in names:
            core.unload_model(name)
        snap = core.hbm.debug_snapshot()
        (dev,) = snap["devices"].values()
        residual = sum(
            sum(components.values())
            for model, components
            in core.devstats.ledger.paged_snapshot().items())
        gate(dev["leased_bytes"] == 0 and not snap["leases"]
             and residual == 0,
             "allocator + ledger residual zero after unload",
             "leased=%d leases=%d paged=%d"
             % (dev["leased_bytes"], len(snap["leases"]), residual))
    finally:
        core.shutdown()

    if FAILURES:
        print("hbm smoke FAILED:")
        for line in FAILURES:
            print("  - %s" % line)
        return 1
    print("hbm smoke passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
