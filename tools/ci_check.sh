#!/usr/bin/env bash
# Tier-1 gate: runs the ROADMAP.md tier-1 pytest command and fails if
# the passed-test count (DOTS_PASSED) drops below the recorded seed
# floor, then runs the chaos smoke (perf harness under fault
# injection with client retries — the "degrades gracefully"
# regression gate). Usage: tools/ci_check.sh [min_passed]
set -u -o pipefail

MIN_PASSED="${1:-750}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/_t1.log

cd "$REPO"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$passed (floor: $MIN_PASSED, pytest rc: $rc)"

if [ "$passed" -lt "$MIN_PASSED" ]; then
    echo "FAIL: passed-test count $passed dropped below the seed floor $MIN_PASSED" >&2
    exit 1
fi
echo "OK: tier-1 no worse than seed"

# Chaos smoke: 25% injected errors at concurrency 4; the run must
# complete (zero hung requests) and the recovery line must appear.
echo "chaos smoke: perf harness under error_rate=0.25 with retries"
CHAOS_LOG=/tmp/_chaos_smoke.log
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python -m client_tpu.perf \
    -m simple --service-kind inprocess --request-count 40 -p 4000 \
    --concurrency-range 4 --chaos "error_rate=0.25,seed=11" --retries 4 \
    > "$CHAOS_LOG" 2>&1; then
    echo "FAIL: chaos smoke run did not complete" >&2
    tail -20 "$CHAOS_LOG" >&2
    exit 1
fi
if ! grep -q "Chaos summary" "$CHAOS_LOG"; then
    echo "FAIL: chaos smoke produced no chaos summary" >&2
    tail -20 "$CHAOS_LOG" >&2
    exit 1
fi
grep -E "Chaos summary|goodput|retries|recovered" "$CHAOS_LOG"
echo "OK: chaos smoke passed"

# Sequence-fusion smoke: 8 concurrent sequences against dyna_sequence
# (oldest strategy) must fuse steps across sequences — the perf
# report's sequence summary must show mean fused batch > 1 (i.e.
# execution_count < request_count on a concurrent-sequence run).
echo "sequence smoke: dyna_sequence fusion at 8 concurrent sequences"
SEQ_LOG=/tmp/_sequence_smoke.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python -m client_tpu.perf \
    -m dyna_sequence --service-kind inprocess --request-count 80 -p 6000 \
    --concurrency-range 8 --sequence-length 10 > "$SEQ_LOG" 2>&1; then
    echo "FAIL: sequence smoke run did not complete" >&2
    tail -20 "$SEQ_LOG" >&2
    exit 1
fi
fused=$(grep -oE "mean fused batch [0-9.]+" "$SEQ_LOG" | tail -1 \
    | awk '{print $4}')
if [ -z "$fused" ]; then
    echo "FAIL: sequence smoke produced no sequence summary" >&2
    tail -20 "$SEQ_LOG" >&2
    exit 1
fi
if ! awk -v f="$fused" 'BEGIN { exit !(f > 1.0) }'; then
    echo "FAIL: sequence steps did not fuse (mean fused batch $fused)" >&2
    grep -E "sequences dyna_sequence|server dyna_sequence" "$SEQ_LOG" >&2
    exit 1
fi
grep -E "sequences dyna_sequence" "$SEQ_LOG"
echo "OK: sequence smoke passed (mean fused batch $fused)"

# Failover smoke: 2 embedded gRPC servers, one chaos-killed 2s into
# the run — the endpoint pool must mask the outage completely (100%
# goodput: zero client-visible errors, all traffic failed over).
echo "failover smoke: 2-server fleet with one endpoint chaos-killed"
FO_LOG=/tmp/_failover_smoke.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python -m client_tpu.perf \
    -m simple --service-kind triton --fleet 2 -i grpc -p 3000 -r 2 \
    --concurrency-range 4 --retries 3 \
    --degrade-one "kill_after_s=2,victim=1" > "$FO_LOG" 2>&1; then
    echo "FAIL: failover smoke run did not complete" >&2
    tail -20 "$FO_LOG" >&2
    exit 1
fi
if ! grep -q "Failover summary" "$FO_LOG"; then
    echo "FAIL: failover smoke produced no failover summary" >&2
    tail -20 "$FO_LOG" >&2
    exit 1
fi
if ! grep -q "client-visible errors: 0 of" "$FO_LOG"; then
    echo "FAIL: endpoint kill was not fully masked by failover" >&2
    grep -E "Failover summary|client-visible|failovers|ejections" \
        "$FO_LOG" >&2
    exit 1
fi
grep -E "Failover summary|client-visible|failovers|ejections" "$FO_LOG"
echo "OK: failover smoke passed (100% goodput through an endpoint kill)"

# Static analysis: one entry point for everything static —
# tpulint's repo-specific checkers (lock-discipline, lock-order,
# resource-pairing, status-literal, retry-after, aio-blocking,
# proto-drift, metrics-doc-drift; docs/static_analysis.md) gated
# against tools/tpulint/baseline.json (zero NEW findings, zero STALE
# baseline entries — an entry whose anchored line changed must be
# pruned), plus the live Prometheus exposition lint
# (tools/metrics_lint.py) via --all.
echo "tpulint: static analysis (zero new findings) + metrics lint"
LINT_LOG=/tmp/_tpulint.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python -m tools.tpulint --all \
    > "$LINT_LOG" 2>&1; then
    echo "FAIL: tpulint/metrics lint failed" >&2
    tail -30 "$LINT_LOG" >&2
    exit 1
fi
grep -E "tpulint passed" "$LINT_LOG"
grep -E "metrics lint passed" "$LINT_LOG"
echo "OK: static analysis passed"

# Telemetry smoke: the always-on latency-histogram layer must (a)
# expose lint-clean histogram families after unary + streaming load,
# (b) estimate a server p99 from bucket deltas within 2x of the
# client-observed p99 of the same window, and (c) cost <2% throughput
# vs recording disabled (paired A/B medians on add_sub_large). Gates
# live in tools/telemetry_smoke.py.
echo "telemetry smoke: histogram presence + quantile fidelity + overhead"
TELEMETRY_LOG=/tmp/_telemetry_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/telemetry_smoke.py \
    > "$TELEMETRY_LOG" 2>&1; then
    echo "FAIL: telemetry smoke did not pass" >&2
    tail -30 "$TELEMETRY_LOG" >&2
    exit 1
fi
grep -E "telemetry smoke passed" "$TELEMETRY_LOG"
echo "OK: telemetry smoke passed"

# Trace smoke: perf run with span tracing at trace_rate=1 — the
# stage-attribution table must be emitted and the instrumented stages
# must account for >=90% of end-to-end server span time (the span
# tree tiles the request; a drop below means an uninstrumented stage
# crept into the serving path).
echo "trace smoke: perf --trace 1 stage attribution on simple"
TRACE_LOG=/tmp/_trace_smoke.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python -m client_tpu.perf \
    -m simple --service-kind inprocess --request-count 40 -p 4000 \
    --concurrency-range 4 --trace 1 > "$TRACE_LOG" 2>&1; then
    echo "FAIL: trace smoke run did not complete" >&2
    tail -20 "$TRACE_LOG" >&2
    exit 1
fi
if ! grep -q "Trace summary" "$TRACE_LOG"; then
    echo "FAIL: trace smoke produced no stage-attribution table" >&2
    tail -20 "$TRACE_LOG" >&2
    exit 1
fi
coverage=$(grep -oE "stage coverage [0-9.]+%" "$TRACE_LOG" | tail -1 \
    | grep -oE "[0-9.]+")
if [ -z "$coverage" ]; then
    echo "FAIL: trace smoke printed no stage-coverage line" >&2
    tail -20 "$TRACE_LOG" >&2
    exit 1
fi
if ! awk -v c="$coverage" 'BEGIN { exit !(c >= 90.0) }'; then
    echo "FAIL: stage attribution covers only ${coverage}% of server" \
         "span time (floor: 90%)" >&2
    grep -A 10 "Trace summary" "$TRACE_LOG" >&2
    exit 1
fi
grep -A 10 "Trace summary" "$TRACE_LOG"
echo "OK: trace smoke passed (stage coverage ${coverage}%)"

# QoS overload smoke: priority-2 bulk saturates a bounded queue while
# a priority-1 foreground keeps sending — priority-1 p99 must stay
# within 2x its unloaded baseline at 100% goodput, the bulk burst
# must actually shed at saturation, and mixed-priority fusion must
# match single-class within 10%. Gates live in tools/qos_smoke.py.
echo "qos smoke: priority-1 under priority-2 saturation + fusion parity"
QOS_LOG=/tmp/_qos_smoke.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/qos_smoke.py \
    > "$QOS_LOG" 2>&1; then
    echo "FAIL: qos smoke did not pass" >&2
    tail -30 "$QOS_LOG" >&2
    exit 1
fi
grep -E "qos smoke passed" "$QOS_LOG"
echo "OK: qos smoke passed"

# Replica chaos smoke: a delay-bound model served as 4 per-device
# replicas, replica 2 hard-degraded mid-run then healed — goodput must
# stay 100% (bounded re-dispatch masks the fault domain), at least one
# ejection + one readmission must be recorded (the self-healing
# supervisor ran), post-recovery throughput must return within 20% of
# pre-fault, and 4 replicas must clear >=2.5x the 1-replica rate.
# Gates live in tools/replica_smoke.py.
echo "replica smoke: 4-replica scaling + kill-one-mid-run self-healing"
REPLICA_LOG=/tmp/_replica_smoke.log
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python tools/replica_smoke.py \
    > "$REPLICA_LOG" 2>&1; then
    echo "FAIL: replica smoke did not pass" >&2
    tail -30 "$REPLICA_LOG" >&2
    exit 1
fi
grep -E "replica smoke passed" "$REPLICA_LOG"
echo "OK: replica smoke passed"

# Cache smoke: hot-set replay against simple_cache — the replayed set
# must reach a 100% hit ratio with hit-path p50 well under miss-path
# p50, and an identical-request burst must execute the model exactly
# once (single-flight dedup). Gates live in tools/cache_smoke.py.
echo "cache smoke: simple_cache hot-set replay + single-flight burst"
CACHE_LOG=/tmp/_cache_smoke.log
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/cache_smoke.py \
    > "$CACHE_LOG" 2>&1; then
    echo "FAIL: cache smoke did not pass" >&2
    tail -20 "$CACHE_LOG" >&2
    exit 1
fi
grep -E "cache smoke passed" "$CACHE_LOG"
echo "OK: cache smoke passed"

# Fetch smoke: the overlapped output-fetch subsystem must hold golden
# parity against the legacy serial np.asarray path (wire + shm-landed
# outputs on the fetch_bench A/B pair), must not regress the
# server-side relay_fetch p50 on real arrays, and must show >=2x
# relay_fetch p50 reduction on a simulated-DMA pair (the overlap
# mechanism itself, platform-independent). Gates live in
# tools/fetch_smoke.py.
echo "fetch smoke: overlapped-vs-legacy relay fetch A/B + parity"
FETCH_LOG=/tmp/_fetch_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/fetch_smoke.py \
    > "$FETCH_LOG" 2>&1; then
    echo "FAIL: fetch smoke did not pass" >&2
    tail -20 "$FETCH_LOG" >&2
    exit 1
fi
grep -E "fetch smoke passed" "$FETCH_LOG"
grep -E "real arrays|simulated DMA" "$FETCH_LOG"
echo "OK: fetch smoke passed"

# Flight-recorder / SLO smoke: chaos latency+error injection at
# trace_rate=0 against simple_slo — >=95% of injected slow/error
# requests must be retained in the flight ring with full span trees
# (tail sampling, no start-time dice roll), tpu_slo_burn_rate must go
# >1 during the injection and recover after, the /v2/debug JSON must
# stay cardinality-bounded, and always-on capture must cost <2%
# throughput (paired A/B on add_sub_large). Gates live in
# tools/flight_smoke.py.
echo "flight smoke: tail retention + SLO burn/recovery + overhead"
FLIGHT_LOG=/tmp/_flight_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/flight_smoke.py \
    > "$FLIGHT_LOG" 2>&1; then
    echo "FAIL: flight smoke did not pass" >&2
    tail -30 "$FLIGHT_LOG" >&2
    exit 1
fi
grep -E "flight smoke passed" "$FLIGHT_LOG"
grep -E "retention:|burn:|recovery:|overhead:" "$FLIGHT_LOG"
echo "OK: flight smoke passed"

# LLM continuous-batching smoke: paged-KV c16 vs the dense c4
# baseline arm on the shared A/B driver — tokens/s >=5x, ITL p99
# <=1.5x, token-exact decode, prefix-cache hits on a shared system
# prompt, and a page pool that is leak-free after cancels and a
# forced crash-recovery. Gates live in tools/llm_smoke.py.
echo "llm smoke: paged-KV continuous batching c16 vs dense c4"
LLM_LOG=/tmp/_llm_smoke.log
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu python tools/llm_smoke.py \
    > "$LLM_LOG" 2>&1; then
    echo "FAIL: llm smoke did not pass" >&2
    tail -30 "$LLM_LOG" >&2
    exit 1
fi
grep -E "llm smoke passed" "$LLM_LOG"
grep -E "dense c4|paged c16" "$LLM_LOG"
echo "OK: llm smoke passed"

# Device-stats smoke: mixed dense + llm + arena load, then the
# device-axis gates — ledger rows sum to tpu_hbm_used_bytes within
# 10% (CPU dryrun: attributed rows present + internally consistent),
# busy-time counter monotonic across two scrapes, >=1 XLA compile
# recorded per fresh model, the /v2/debug/profile endpoint returns a
# loadable chrome trace of a live window, and always-on recording
# costs <2% throughput (paired A/B). Gates live in
# tools/devstats_smoke.py.
echo "devstats smoke: HBM ledger + busy/duty + compiles + profiler"
DEVSTATS_LOG=/tmp/_devstats_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/devstats_smoke.py \
    > "$DEVSTATS_LOG" 2>&1; then
    echo "FAIL: devstats smoke did not pass" >&2
    tail -30 "$DEVSTATS_LOG" >&2
    exit 1
fi
grep -E "devstats smoke passed" "$DEVSTATS_LOG"
grep -E "ledger|busy|compile recorded|overhead" "$DEVSTATS_LOG" | head -10
echo "OK: devstats smoke passed"

# Autoscale smoke: a controller-governed model (min 1 / max 4
# replicas) under a 10x diurnal swing (chaos trace mode) with one
# replica chaos-killed mid-swing — priority-1 p99 must stay within
# the configured SLO, replica-seconds consumed must be <= 0.6x of a
# max-scale-always fleet, >= 1 scale-up and >= 1 scale-down must fire
# with flight-recorded decisions in both directions, and the kill must
# be fully masked (0 foreground errors). Gates live in
# tools/autoscale_smoke.py.
echo "autoscale smoke: 10x diurnal swing + mid-swing kill vs controller"
AUTOSCALE_LOG=/tmp/_autoscale_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/autoscale_smoke.py \
    > "$AUTOSCALE_LOG" 2>&1; then
    echo "FAIL: autoscale smoke did not pass" >&2
    tail -30 "$AUTOSCALE_LOG" >&2
    exit 1
fi
grep -E "autoscale smoke passed" "$AUTOSCALE_LOG"
echo "OK: autoscale smoke passed"

# Ensemble-dataflow smoke: the ensemble_ab / ensemble_ab_legacy A/B
# pair on the shared driver — golden parity across arms, backbone
# fusion ratio <= 0.15 at c16 (per-stage batching), hot-set
# throughput >= 4x legacy (stage-cache subgraph short-circuit), and
# a traced request with ensemble_step spans and zero relay_fetch.
# Gates live in tools/ensemble_smoke.py.
echo "ensemble smoke: device-resident dataflow vs legacy step loop"
ENSEMBLE_LOG=/tmp/_ensemble_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/ensemble_smoke.py \
    > "$ENSEMBLE_LOG" 2>&1; then
    echo "FAIL: ensemble smoke did not pass" >&2
    tail -30 "$ENSEMBLE_LOG" >&2
    exit 1
fi
grep -E "ensemble smoke passed" "$ENSEMBLE_LOG"
grep -E "distinct c|hot set|trace:" "$ENSEMBLE_LOG"
echo "OK: ensemble smoke passed"

# HBM-allocator smoke: 9 pageable models against a simulated
# CLIENT_TPU_HBM_BUDGET that fits 3, hot-set workload while the cold
# tail churns through admission-miss restores — zero evictions of
# hot components during churn (heat-aware LRU), hot p99 within 5x of
# the quiet baseline, cold first-request wall time within the
# advertised restore-bandwidth bound, response parity after every
# page-out/restore round trip, and allocator + ledger residual zero
# after unloading everything. Gates live in tools/hbm_smoke.py.
echo "hbm smoke: oversubscribed weight paging vs hot-set workload"
HBM_LOG=/tmp/_hbm_smoke.log
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/hbm_smoke.py \
    > "$HBM_LOG" 2>&1; then
    echo "FAIL: hbm smoke did not pass" >&2
    tail -30 "$HBM_LOG" >&2
    exit 1
fi
grep -E "hbm smoke passed" "$HBM_LOG"
grep -E "hot p99|cold first-request|residual" "$HBM_LOG"
echo "OK: hbm smoke passed"

# Cancellation smoke: abandoned-request storm A/B — the cancel arm
# must waste <= 0.4x the ignore-cancels arm on work whose caller
# already left, survivor p99 within 1.2x the no-abandon baseline,
# zero leaked tenant slots / KV pages / allocator+ledger bytes after
# the storm drains, and the always-on token mint + stage checks under
# 2% hot-path overhead. Gates live in tools/cancel_smoke.py.
echo "cancel smoke: abandoned-request storm A/B + leak + overhead"
CANCEL_LOG=/tmp/_cancel_smoke.log
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu python tools/cancel_smoke.py \
    > "$CANCEL_LOG" 2>&1; then
    echo "FAIL: cancel smoke did not pass" >&2
    tail -30 "$CANCEL_LOG" >&2
    exit 1
fi
grep -E "cancel smoke passed" "$CANCEL_LOG"
echo "OK: cancel smoke passed"

# Mesh smoke: sharded serving on the 8-device simulated platform —
# a model too big for any one device's budget admits as per-device
# slice leases, a tp=4-sharded LLM holds golden parity with the
# single-device model and its sharded paged-KV pool is leak-free
# after cancel churn, 2 tp slices clear >=1.8x the 1-slice rate, and
# a chaos-killed chip ejects its whole slice (100% goodput via the
# sibling) then readmits. Gates live in tools/mesh_smoke.py.
echo "mesh smoke: sharded slices — scaling + kill-one-chip + parity"
MESH_LOG=/tmp/_mesh_smoke.log
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/mesh_smoke.py > "$MESH_LOG" 2>&1; then
    echo "FAIL: mesh smoke did not pass" >&2
    tail -30 "$MESH_LOG" >&2
    exit 1
fi
grep -E "mesh smoke passed" "$MESH_LOG"
echo "OK: mesh smoke passed"
exit 0
