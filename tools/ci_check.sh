#!/usr/bin/env bash
# Tier-1 gate: runs the ROADMAP.md tier-1 pytest command and fails if
# the passed-test count (DOTS_PASSED) drops below the recorded seed
# floor. Usage: tools/ci_check.sh [min_passed]
set -u -o pipefail

MIN_PASSED="${1:-290}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/_t1.log

cd "$REPO"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$passed (floor: $MIN_PASSED, pytest rc: $rc)"

if [ "$passed" -lt "$MIN_PASSED" ]; then
    echo "FAIL: passed-test count $passed dropped below the seed floor $MIN_PASSED" >&2
    exit 1
fi
echo "OK: tier-1 no worse than seed"
exit 0
