"""Regenerate the proto additions in inference_pb2.py / model_config_pb2.py.

The container image carries no protoc / grpcio-tools, so proto schema
changes are applied by editing the serialized FileDescriptorProto that
each ``*_pb2.py`` embeds: parse it with ``descriptor_pb2``, add the
new messages + fields, re-serialize, and rewrite the
``AddSerializedFile`` bytes literal in place.  Idempotent — running it
again on an already patched file is a no-op.

Patches applied:

* inference_pb2.py — ``BatchPipelineStatistics`` +
  ``ModelStatistics.pipeline_stats`` (PR 1), the queue-policy drop
  counters ``ModelStatistics.reject_count`` /
  ``ModelStatistics.timeout_count`` (PR 2),
  ``SequenceBatchingStatistics`` + ``ModelStatistics.sequence_stats``
  (PR 3 sequence scheduler), the response-cache statistics (PR 5):
  ``ModelStatistics.cache_hit_count`` / ``cache_miss_count`` plus the
  ``InferStatistics.cache_hit`` / ``cache_miss`` durations, and the
  QoS statistics (PR 7): ``ModelStatistics.shed_count`` plus the
  repeated per-class ``PriorityStatistics`` / ``TenantStatistics``
  rows, and the replica-serving statistics (PR 8): repeated
  per-fault-domain ``ReplicaStatistics`` rows plus
  ``ModelStatistics.healthy_replicas`` / ``total_replicas``, and the
  SLO engine rows (PR 14): ``SloStatistics`` +
  ``ModelStatistics.slo_stats``.
* model_config_pb2.py — ``DynamicBatchingConfig.max_queue_size`` /
  ``allow_timeout_override`` / ``timeout_action`` (PR 2 queue policy;
  ``default_queue_policy_timeout_us`` has been in the schema since the
  seed), the full sequence-batching schema (PR 3):
  ``SequenceControlInput`` / ``SequenceStateConfig`` messages plus
  ``SequenceBatchingConfig.strategy`` / ``control_input`` / ``state`` /
  ``preferred_batch_size``, the ``ResponseCacheConfig`` message +
  ``ModelConfig.response_cache`` (PR 5 response cache), and the
  multi-tenant QoS schema (PR 7): ``DynamicBatchingConfig.
  priority_levels`` / ``default_priority_level`` / ``shed_watermark``
  plus the per-priority ``PriorityQueuePolicy`` rows, the SLO
  declaration (PR 14): ``SloConfig`` + ``ModelConfig.slo``, the
  autoscale declaration (PR 17): ``AutoscaleConfig`` +
  ``ModelInstanceConfig.autoscale``, and the mesh-slice declaration
  (PR 20): ``ModelInstanceConfig.shard_mesh`` (reusing the base
  schema's ``MeshConfig``).

The ``_serialized_start/_serialized_end`` attribute lines at the bottom
of the pb2 modules go stale after the patch; they only execute when
``_USE_C_DESCRIPTORS`` is False, which is never the case on the upb
runtime this image ships (protobuf >= 4), so they are left untouched.

Usage: python tools/extend_inference_proto.py
"""

from __future__ import annotations

import pathlib
import re
import sys

from google.protobuf import descriptor_pb2

REPO = pathlib.Path(__file__).resolve().parents[1]
PB2_PATH = REPO / "client_tpu" / "protocol" / "inference_pb2.py"
MODEL_CONFIG_PB2_PATH = REPO / "client_tpu" / "protocol" / "model_config_pb2.py"

U64 = descriptor_pb2.FieldDescriptorProto.TYPE_UINT64
I64 = descriptor_pb2.FieldDescriptorProto.TYPE_INT64
DOUBLE = descriptor_pb2.FieldDescriptorProto.TYPE_DOUBLE
MESSAGE = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
ENUM = descriptor_pb2.FieldDescriptorProto.TYPE_ENUM
BOOL = descriptor_pb2.FieldDescriptorProto.TYPE_BOOL
STRING = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
OPTIONAL = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
REPEATED = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED

# (name, number, type) — keep in sync with inference.proto.
PIPELINE_FIELDS = [
    ("pending_count", 1, U64),
    ("inflight_count", 2, U64),
    ("queue_delay_us", 3, U64),
    ("compute_ns", 4, U64),
    ("fetch_ns", 5, U64),
    ("overlap_ns", 6, U64),
    ("overlap_ratio", 7, DOUBLE),
]

# Queue-policy drop counters on ModelStatistics (pipeline_stats is 8).
STATISTICS_FIELDS = [
    ("reject_count", 9, U64),
    ("timeout_count", 10, U64),
]

# Response-cache counters on ModelStatistics (11 is sequence_stats).
CACHE_COUNT_FIELDS = [
    ("cache_hit_count", 12, U64),
    ("cache_miss_count", 13, U64),
]

# QoS drop counter on ModelStatistics (14; 15/16 are the repeated
# per-class statistics rows below).
QOS_COUNT_FIELDS = [
    ("shed_count", 14, U64),
]

# Per-priority-class counters (one row per level that saw traffic).
PRIORITY_STATS_FIELDS = [
    ("priority_level", 1, U64),
    ("success_count", 2, U64),
    ("reject_count", 3, U64),
    ("timeout_count", 4, U64),
    ("shed_count", 5, U64),
    ("queue_ns", 6, U64),
]

# Per-tenant counters (one row per tenant this model served).
TENANT_STATS_FIELDS = [
    ("tenant", 1, STRING),
    ("success_count", 2, U64),
    ("reject_count", 3, U64),
    ("fail_count", 4, U64),
    ("duration_ns", 5, U64),
]

# Per-replica rows (PR 8 replica serving): one row per fault domain of
# an instance-group model, fed by ReplicaSet.snapshot().
REPLICA_STATS_FIELDS = [
    ("replica_index", 1, U64),
    ("healthy", 2, BOOL),
    ("request_count", 3, U64),
    ("failure_count", 4, U64),
    ("execution_count", 5, U64),
    ("exec_ns", 6, U64),
    ("ejected_count", 7, U64),
    ("readmitted_count", 8, U64),
]

# Replica-set health summary on ModelStatistics (17 is the repeated
# replica_stats rows above).
REPLICA_COUNT_FIELDS = [
    ("healthy_replicas", 18, U64),
    ("total_replicas", 19, U64),
]

# Streaming-token telemetry (PR 10): completed streams, responses
# streamed, and the server-observed TTFT / inter-response sums
# (StatisticDuration count+ns pairs). ModelStatistics.stream_stats is
# field 20.
STREAM_STATS_FIELDS = [
    ("stream_count", 1, U64, None),
    ("response_count", 2, U64, None),
    ("first_response", 3, MESSAGE, ".inference.StatisticDuration"),
    ("inter_response", 4, MESSAGE, ".inference.StatisticDuration"),
]

# Response-cache path durations on InferStatistics (1..6 are the
# Triton-parity sections present since the seed).
CACHE_DURATION_FIELDS = [
    ("cache_hit", 7),
    ("cache_miss", 8),
]

# Device-axis rows (PR 15): per-model HBM attribution from the device
# ledger (client_tpu/server/devstats.py) plus compile telemetry.
# ModelStatistics.device_stats is field 22.
DEVICE_HBM_COMPONENT_FIELDS = [
    ("component", 1, STRING),
    ("hbm_bytes", 2, U64),
]
DEVICE_STATS_FIELDS = [
    ("hbm_bytes", 1, U64),
    ("compile_count", 3, U64),
    ("compile_ns", 4, U64),
]

# SLO engine rows (PR 14): declared targets + multi-window burn rates
# computed by client_tpu/server/slo.py. ModelStatistics.slo_stats is
# field 21.
SLO_STATS_FIELDS = [
    ("p99_latency_target_us", 1, U64),
    ("ttft_p99_target_us", 2, U64),
    ("availability_target", 3, DOUBLE),
    ("burn_rate_fast", 4, DOUBLE),
    ("burn_rate_slow", 5, DOUBLE),
    ("budget_remaining", 6, DOUBLE),
    ("healthy", 7, BOOL),
]

# Queue-policy knobs on DynamicBatchingConfig (field 3 is
# default_queue_policy_timeout_us, present since the seed).
QUEUE_POLICY_FIELDS = [
    ("max_queue_size", 4, U64),
    ("allow_timeout_override", 5, BOOL),
    ("timeout_action", 6, STRING),
]

# Multi-tenant QoS knobs on DynamicBatchingConfig (Triton
# priority_levels semantics: classes 1..priority_levels, 1 highest;
# shed_watermark is the queue-depth fraction at which lowest-class
# shedding starts). priority_queue_policy (field 9) is added
# separately — it is a repeated message.
PRIORITY_FIELDS = [
    ("priority_levels", 7, U64),
    ("default_priority_level", 8, U64),
    ("shed_watermark", 10, DOUBLE),
]

# Per-priority ModelQueuePolicy overrides (the map<uint64,
# ModelQueuePolicy> of Triton's schema, flattened to repeated rows so
# the descriptor patch stays map-entry-free).
PRIORITY_POLICY_FIELDS = [
    ("priority_level", 1, U64),
    ("max_queue_size", 2, U64),
    ("default_timeout_us", 3, U64),
]

# Per-model SLO declaration (PR 14): the `slo` block on ModelConfig
# (field 16) the burn-rate engine reads its targets from.
SLO_CONFIG_FIELDS = [
    ("p99_latency_us", 1, U64),
    ("ttft_p99_us", 2, U64),
    ("availability", 3, DOUBLE),
]

# Autoscale controller declaration (PR 17): per-instance-group
# feedback-loop bounds and hysteresis knobs, rendered as
# ModelInstanceConfig.autoscale (client_tpu.server.autoscale).
AUTOSCALE_CONFIG_FIELDS = [
    ("min_replicas", 1, U64),
    ("max_replicas", 2, U64),
    ("interval_s", 3, DOUBLE),
    ("queue_high", 4, DOUBLE),
    ("duty_high", 5, DOUBLE),
    ("duty_low", 6, DOUBLE),
    ("up_cooldown_s", 7, DOUBLE),
    ("down_cooldown_s", 8, DOUBLE),
    ("idle_s", 9, DOUBLE),
]

# Sequence-scheduler observability on ModelStatistics (field 11;
# 8/9/10 are pipeline_stats / reject_count / timeout_count).
SEQUENCE_STATS_FIELDS = [
    ("active_sequences", 1, U64),
    ("slot_total", 2, U64),
    ("backlog_depth", 3, U64),
    ("idle_reclaimed_total", 4, U64),
    ("sequences_started", 5, U64),
    ("sequences_completed", 6, U64),
    ("step_count", 7, U64),
    ("fused_steps", 8, U64),
]

# (name, number, type, label, type_name) rows for the sequence-batching
# schema messages — keep in sync with model_config.proto.
CONTROL_INPUT_FIELDS = [
    ("name", 1, STRING, OPTIONAL, None),
    ("kind", 2, STRING, OPTIONAL, None),
    ("data_type", 3, ENUM, OPTIONAL, ".inference.TensorDataType"),
]
STATE_CONFIG_FIELDS = [
    ("input_name", 1, STRING, OPTIONAL, None),
    ("output_name", 2, STRING, OPTIONAL, None),
    ("data_type", 3, ENUM, OPTIONAL, ".inference.TensorDataType"),
    ("dims", 4, I64, REPEATED, None),
]
SEQUENCE_BATCHING_FIELDS = [
    ("strategy", 3, STRING, OPTIONAL, None),
    ("control_input", 4, MESSAGE, REPEATED,
     ".inference.SequenceControlInput"),
    ("state", 5, MESSAGE, REPEATED, ".inference.SequenceStateConfig"),
    ("preferred_batch_size", 6, I64, REPEATED, None),
]


def extract_serialized(source: str, path: pathlib.Path) -> bytes:
    match = re.search(r"AddSerializedFile\((b'.*')\)", source)
    if not match:
        raise SystemExit("no AddSerializedFile literal found in %s" % path)
    return eval(match.group(1))  # noqa: S307 — a bytes literal we just matched


def patch_inference(file_proto: descriptor_pb2.FileDescriptorProto) -> bool:
    names = [m.name for m in file_proto.message_type]
    changed = False
    if "BatchPipelineStatistics" not in names:
        # Insert right after InferBatchStatistics (placement is
        # cosmetic; descriptor resolution is order-independent).
        anchor = names.index("InferBatchStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name="BatchPipelineStatistics")
        for name, number, ftype in PIPELINE_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    model_stats = next(
        m for m in file_proto.message_type if m.name == "ModelStatistics")
    if not any(f.name == "pipeline_stats" for f in model_stats.field):
        model_stats.field.add(
            name="pipeline_stats", number=8, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.BatchPipelineStatistics",
            json_name="pipelineStats")
        changed = True
    for name, number, ftype in STATISTICS_FIELDS:
        if not any(f.name == name for f in model_stats.field):
            model_stats.field.add(name=name, number=number, type=ftype,
                                  label=OPTIONAL, json_name=_json_name(name))
            changed = True
    names = [m.name for m in file_proto.message_type]
    if "SequenceBatchingStatistics" not in names:
        anchor = names.index("BatchPipelineStatistics") + 1
        message = descriptor_pb2.DescriptorProto(
            name="SequenceBatchingStatistics")
        for name, number, ftype in SEQUENCE_STATS_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    if not any(f.name == "sequence_stats" for f in model_stats.field):
        model_stats.field.add(
            name="sequence_stats", number=11, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.SequenceBatchingStatistics",
            json_name="sequenceStats")
        changed = True
    for name, number, ftype in CACHE_COUNT_FIELDS + QOS_COUNT_FIELDS:
        if not any(f.name == name for f in model_stats.field):
            model_stats.field.add(name=name, number=number, type=ftype,
                                  label=OPTIONAL, json_name=_json_name(name))
            changed = True
    names = [m.name for m in file_proto.message_type]
    for msg_name, rows in (
        ("PriorityStatistics", PRIORITY_STATS_FIELDS),
        ("TenantStatistics", TENANT_STATS_FIELDS),
        ("ReplicaStatistics", REPLICA_STATS_FIELDS),
    ):
        if msg_name in names:
            continue
        anchor = names.index("SequenceBatchingStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name=msg_name)
        for name, number, ftype in rows:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        names.insert(anchor, msg_name)
        changed = True
    for field_name, number, type_name in (
        ("priority_stats", 15, ".inference.PriorityStatistics"),
        ("tenant_stats", 16, ".inference.TenantStatistics"),
        ("replica_stats", 17, ".inference.ReplicaStatistics"),
    ):
        if not any(f.name == field_name for f in model_stats.field):
            model_stats.field.add(
                name=field_name, number=number, type=MESSAGE,
                label=REPEATED, type_name=type_name,
                json_name=_json_name(field_name))
            changed = True
    for name, number, ftype in REPLICA_COUNT_FIELDS:
        if not any(f.name == name for f in model_stats.field):
            model_stats.field.add(name=name, number=number, type=ftype,
                                  label=OPTIONAL, json_name=_json_name(name))
            changed = True
    names = [m.name for m in file_proto.message_type]
    if "StreamStatistics" not in names:
        anchor = names.index("SequenceBatchingStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name="StreamStatistics")
        for name, number, ftype, type_name in STREAM_STATS_FIELDS:
            field = message.field.add(name=name, number=number,
                                      type=ftype, label=OPTIONAL,
                                      json_name=_json_name(name))
            if type_name:
                field.type_name = type_name
        file_proto.message_type.insert(anchor, message)
        changed = True
    if not any(f.name == "stream_stats" for f in model_stats.field):
        model_stats.field.add(
            name="stream_stats", number=20, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.StreamStatistics",
            json_name="streamStats")
        changed = True
    names = [m.name for m in file_proto.message_type]
    if "SloStatistics" not in names:
        anchor = names.index("StreamStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name="SloStatistics")
        for name, number, ftype in SLO_STATS_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    if not any(f.name == "slo_stats" for f in model_stats.field):
        model_stats.field.add(
            name="slo_stats", number=21, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.SloStatistics",
            json_name="sloStats")
        changed = True
    names = [m.name for m in file_proto.message_type]
    if "DeviceHbmComponent" not in names:
        anchor = names.index("SloStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name="DeviceHbmComponent")
        for name, number, ftype in DEVICE_HBM_COMPONENT_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        names.insert(anchor, "DeviceHbmComponent")
        changed = True
    if "DeviceStatistics" not in names:
        anchor = names.index("DeviceHbmComponent") + 1
        message = descriptor_pb2.DescriptorProto(name="DeviceStatistics")
        for name, number, ftype in DEVICE_STATS_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        message.field.add(
            name="components", number=2, type=MESSAGE, label=REPEATED,
            type_name=".inference.DeviceHbmComponent",
            json_name="components")
        file_proto.message_type.insert(anchor, message)
        changed = True
    if not any(f.name == "device_stats" for f in model_stats.field):
        model_stats.field.add(
            name="device_stats", number=22, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.DeviceStatistics",
            json_name="deviceStats")
        changed = True
    infer_stats = next(
        m for m in file_proto.message_type if m.name == "InferStatistics")
    for name, number in CACHE_DURATION_FIELDS:
        if not any(f.name == name for f in infer_stats.field):
            infer_stats.field.add(
                name=name, number=number, type=MESSAGE, label=OPTIONAL,
                type_name=".inference.StatisticDuration",
                json_name=_json_name(name))
            changed = True
    return changed


def patch_model_config(file_proto: descriptor_pb2.FileDescriptorProto) -> bool:
    batching = next(
        m for m in file_proto.message_type
        if m.name == "DynamicBatchingConfig")
    changed = False
    for name, number, ftype in QUEUE_POLICY_FIELDS + PRIORITY_FIELDS:
        if not any(f.name == name for f in batching.field):
            batching.field.add(name=name, number=number, type=ftype,
                               label=OPTIONAL, json_name=_json_name(name))
            changed = True
    names = [m.name for m in file_proto.message_type]
    if "PriorityQueuePolicy" not in names:
        anchor = names.index("DynamicBatchingConfig")
        message = descriptor_pb2.DescriptorProto(name="PriorityQueuePolicy")
        for name, number, ftype in PRIORITY_POLICY_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        names.insert(anchor, "PriorityQueuePolicy")
        changed = True
    if not any(f.name == "priority_queue_policy" for f in batching.field):
        batching.field.add(
            name="priority_queue_policy", number=9, type=MESSAGE,
            label=REPEATED, type_name=".inference.PriorityQueuePolicy",
            json_name="priorityQueuePolicy")
        changed = True
    anchor = names.index("SequenceBatchingConfig")
    for msg_name, rows in (
        ("SequenceControlInput", CONTROL_INPUT_FIELDS),
        ("SequenceStateConfig", STATE_CONFIG_FIELDS),
    ):
        if msg_name in names:
            continue
        message = descriptor_pb2.DescriptorProto(name=msg_name)
        for name, number, ftype, label, type_name in rows:
            field = message.field.add(name=name, number=number, type=ftype,
                                      label=label,
                                      json_name=_json_name(name))
            if type_name:
                field.type_name = type_name
        file_proto.message_type.insert(anchor, message)
        anchor += 1
        changed = True
    sequence = next(
        m for m in file_proto.message_type
        if m.name == "SequenceBatchingConfig")
    for name, number, ftype, label, type_name in SEQUENCE_BATCHING_FIELDS:
        if any(f.name == name for f in sequence.field):
            continue
        field = sequence.field.add(name=name, number=number, type=ftype,
                                   label=label, json_name=_json_name(name))
        if type_name:
            field.type_name = type_name
        changed = True
    names = [m.name for m in file_proto.message_type]
    if "ResponseCacheConfig" not in names:
        anchor = names.index("EnsembleStepConfig")
        message = descriptor_pb2.DescriptorProto(name="ResponseCacheConfig")
        message.field.add(name="enable", number=1, type=BOOL,
                          label=OPTIONAL, json_name="enable")
        file_proto.message_type.insert(anchor, message)
        changed = True
    model_config = next(
        m for m in file_proto.message_type if m.name == "ModelConfig")
    if not any(f.name == "response_cache" for f in model_config.field):
        model_config.field.add(
            name="response_cache", number=15, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.ResponseCacheConfig",
            json_name="responseCache")
        changed = True
    names = [m.name for m in file_proto.message_type]
    if "SloConfig" not in names:
        anchor = names.index("ModelConfig")
        message = descriptor_pb2.DescriptorProto(name="SloConfig")
        for name, number, ftype in SLO_CONFIG_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    if not any(f.name == "slo" for f in model_config.field):
        model_config.field.add(
            name="slo", number=16, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.SloConfig", json_name="slo")
        changed = True
    names = [m.name for m in file_proto.message_type]
    if "AutoscaleConfig" not in names:
        anchor = names.index("ModelInstanceConfig")
        message = descriptor_pb2.DescriptorProto(name="AutoscaleConfig")
        for name, number, ftype in AUTOSCALE_CONFIG_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    instance_group = next(
        m for m in file_proto.message_type
        if m.name == "ModelInstanceConfig")
    if not any(f.name == "autoscale" for f in instance_group.field):
        instance_group.field.add(
            name="autoscale", number=5, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.AutoscaleConfig", json_name="autoscale")
        changed = True
    # Mesh-slice serving (PR 20): the replica axis composes with a
    # shard mesh — each instance_group replica is a slice of
    # product(axis_sizes) devices. Reuses the existing MeshConfig
    # message (already in the base descriptor for model-level mesh).
    if not any(f.name == "shard_mesh" for f in instance_group.field):
        instance_group.field.add(
            name="shard_mesh", number=6, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.MeshConfig", json_name="shardMesh")
        changed = True
    return changed


def _json_name(snake: str) -> str:
    head, *rest = snake.split("_")
    return head + "".join(part.capitalize() for part in rest)


def _apply(path: pathlib.Path, patcher) -> None:
    source = path.read_text()
    file_proto = descriptor_pb2.FileDescriptorProto()
    file_proto.ParseFromString(extract_serialized(source, path))
    if not patcher(file_proto):
        print("%s already patched; nothing to do" % path)
        return
    new_literal = repr(file_proto.SerializeToString())
    assert new_literal.startswith("b'") and new_literal.endswith("'")
    new_source = re.sub(
        r"AddSerializedFile\(b'.*'\)",
        lambda _: "AddSerializedFile(%s)" % new_literal,
        source,
    )
    path.write_text(new_source)
    print("patched %s" % path)


def main() -> None:
    _apply(PB2_PATH, patch_inference)
    _apply(MODEL_CONFIG_PB2_PATH, patch_model_config)


if __name__ == "__main__":
    sys.exit(main())
