"""Regenerate the BatchPipelineStatistics additions in inference_pb2.py.

The container image carries no protoc / grpcio-tools, so proto schema
changes are applied by editing the serialized FileDescriptorProto that
``inference_pb2.py`` embeds: parse it with ``descriptor_pb2``, add the
new message + field, re-serialize, and rewrite the ``AddSerializedFile``
bytes literal in place.  Idempotent — running it again on an already
patched file is a no-op.

The ``_serialized_start/_serialized_end`` attribute lines at the bottom
of the pb2 module go stale after the patch; they only execute when
``_USE_C_DESCRIPTORS`` is False, which is never the case on the upb
runtime this image ships (protobuf >= 4), so they are left untouched.

Usage: python tools/extend_inference_proto.py
"""

from __future__ import annotations

import pathlib
import re
import sys

from google.protobuf import descriptor_pb2

REPO = pathlib.Path(__file__).resolve().parents[1]
PB2_PATH = REPO / "client_tpu" / "protocol" / "inference_pb2.py"

U64 = descriptor_pb2.FieldDescriptorProto.TYPE_UINT64
DOUBLE = descriptor_pb2.FieldDescriptorProto.TYPE_DOUBLE
MESSAGE = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
OPTIONAL = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

# (name, number, type) — keep in sync with inference.proto.
PIPELINE_FIELDS = [
    ("pending_count", 1, U64),
    ("inflight_count", 2, U64),
    ("queue_delay_us", 3, U64),
    ("compute_ns", 4, U64),
    ("fetch_ns", 5, U64),
    ("overlap_ns", 6, U64),
    ("overlap_ratio", 7, DOUBLE),
]


def extract_serialized(source: str) -> bytes:
    match = re.search(r"AddSerializedFile\((b'.*')\)", source)
    if not match:
        raise SystemExit("no AddSerializedFile literal found in %s" % PB2_PATH)
    return eval(match.group(1))  # noqa: S307 — a bytes literal we just matched


def patch(file_proto: descriptor_pb2.FileDescriptorProto) -> bool:
    names = [m.name for m in file_proto.message_type]
    changed = False
    if "BatchPipelineStatistics" not in names:
        # Insert right after InferBatchStatistics (placement is
        # cosmetic; descriptor resolution is order-independent).
        anchor = names.index("InferBatchStatistics") + 1
        message = descriptor_pb2.DescriptorProto(name="BatchPipelineStatistics")
        for name, number, ftype in PIPELINE_FIELDS:
            message.field.add(name=name, number=number, type=ftype,
                              label=OPTIONAL, json_name=_json_name(name))
        file_proto.message_type.insert(anchor, message)
        changed = True
    model_stats = next(
        m for m in file_proto.message_type if m.name == "ModelStatistics")
    if not any(f.name == "pipeline_stats" for f in model_stats.field):
        model_stats.field.add(
            name="pipeline_stats", number=8, type=MESSAGE, label=OPTIONAL,
            type_name=".inference.BatchPipelineStatistics",
            json_name="pipelineStats")
        changed = True
    return changed


def _json_name(snake: str) -> str:
    head, *rest = snake.split("_")
    return head + "".join(part.capitalize() for part in rest)


def main() -> None:
    source = PB2_PATH.read_text()
    file_proto = descriptor_pb2.FileDescriptorProto()
    file_proto.ParseFromString(extract_serialized(source))
    if not patch(file_proto):
        print("inference_pb2.py already patched; nothing to do")
        return
    new_literal = repr(file_proto.SerializeToString())
    assert new_literal.startswith("b'") and new_literal.endswith("'")
    new_source = re.sub(
        r"AddSerializedFile\(b'.*'\)",
        lambda _: "AddSerializedFile(%s)" % new_literal,
        source,
    )
    PB2_PATH.write_text(new_source)
    print("patched %s (+BatchPipelineStatistics, "
          "+ModelStatistics.pipeline_stats)" % PB2_PATH)


if __name__ == "__main__":
    sys.exit(main())
