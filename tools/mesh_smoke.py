"""Mesh-slice serving smoke gate for tools/ci_check.sh
(docs/sharded_serving.md).

Runs on the 8-device simulated CPU platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) and gates the
ISSUE-20 acceptance criteria:

* **Slice scaling + kill-one-chip** (bench_child.run_mesh_measure): a
  delay-bound model served as 1 vs 2 tp-sharded slices must scale >=
  1.8x; chaos ``device=0`` mid-load must be fully masked (100%
  goodput — every failure re-dispatched to the sibling slice) with the
  whole slice ejected AND readmitted after the chip heals.
* **Too-big-for-one-device admission**: against a per-device HBM
  budget smaller than the model, whole-model admission on one device
  is refused while slice admission (per-device shard shares) succeeds
  — the model serves BECAUSE it is sharded.
* **Golden parity**: a tp=4-sharded LLM's greedy token stream is
  byte-identical to the single-device model's.
* **Sharded paged KV**: the page pool serves sharded (page axis over
  tp) and returns to zero pages after completion + cancel churn.

The throughput-ratio gate divides two measurements on a shared CI
box, so one retry is allowed; every correctness gate must hold on
each attempt.

Usage: JAX_PLATFORMS=cpu python tools/mesh_smoke.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def check_budget_proof() -> list:
    """The model only fits sharded: one device refuses the whole
    model, slice admission lands every per-device share."""
    import numpy as np

    from client_tpu.server import devstats as devstats_mod
    from client_tpu.server import hbm as hbm_mod
    from client_tpu.server import mesh as mesh_mod
    from client_tpu.utils import InferenceServerException

    class _Big:
        def __init__(self):
            self.weights = np.zeros(1 << 18, dtype=np.float32)  # 1 MiB

    failures = []
    allocator = hbm_mod.HbmAllocator(
        budget_bytes=512 << 10,  # half the model per device
        stats=devstats_mod.DeviceStats(enabled=True))
    saved = hbm_mod._SINGLETON
    hbm_mod._SINGLETON = allocator
    try:
        try:
            allocator.lease("big", "weights", 1 << 20,
                            device_key="CPU-0")
            failures.append("whole-model lease fit a 512K device "
                            "budget — the too-big premise is broken")
        except InferenceServerException:
            pass
        mesh_slice = mesh_mod.plan_slice([("tp", 4)], 0)
        resources = mesh_mod.admit_slice("big", mesh_slice, _Big())
        if len(resources.leases) != 4:
            failures.append("slice admission booked %d leases "
                            "(want 4 — one per member device)"
                            % len(resources.leases))
        devices = sorted({lease.device_key
                          for lease in resources.leases})
        if len(devices) != 4:
            failures.append("slice leases landed on %s (want 4 "
                            "distinct member devices)" % devices)
        resources.release()
        if allocator._by_model.get("big"):
            failures.append("slice release left residual leases")
    finally:
        hbm_mod._SINGLETON = saved
    return failures


def check_llm_parity_and_sharded_kv() -> list:
    """tp=4 parity vs single device + sharded paged pool returning to
    zero pages after completion and cancel churn."""
    import jax
    import numpy as np

    from client_tpu.models.llm import LlmConfig, LlmModel
    from client_tpu.parallel import create_mesh

    def gen(model, prompt, n=8):
        return [t for t in model._generate(
            {"text_input": np.array([prompt], dtype=np.object_),
             "max_tokens": np.array([n], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})]

    def drain(model, timeout_s=30.0):
        import time

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            snap = model.kv_stats()
            if not (snap["pages_used"] or snap["pages_reserved"]
                    or model._active):
                return snap
            time.sleep(0.05)
        return model.kv_stats()

    failures = []
    cfg = LlmConfig(vocab=264, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=4, d_ff=128, max_seq=64)
    mesh = create_mesh((("tp", 4),), devices=jax.devices()[:4])
    single = LlmModel(name="mesh_smoke_one", cfg=cfg,
                      decode_lanes=2, page_size=4, kv_pages=16)
    sharded = LlmModel(name="mesh_smoke_tp4", cfg=cfg, mesh=mesh,
                       decode_lanes=2, page_size=4, kv_pages=16)
    try:
        if not sharded._paged:
            failures.append("sharded LLM fell back to the dense arm "
                            "(paged pool must shard its page axis)")
        for prompt in (b"mesh smoke", b"sharded parity probe " * 2):
            if gen(single, prompt) != gen(sharded, prompt):
                failures.append("sharded output diverged from the "
                                "single-device model on %r" % prompt)
        # Cancel churn: abandon a stream mid-decode, then drain.
        stream = sharded._generate(
            {"text_input": np.array([b"abandoned stream"],
                                    dtype=np.object_),
             "max_tokens": np.array([40], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})
        next(stream)
        stream.close()
        snap = drain(sharded)
        if snap["pages_used"] or snap["pages_reserved"]:
            failures.append(
                "sharded pool leaked pages after churn: %d used, "
                "%d reserved"
                % (snap["pages_used"], snap["pages_reserved"]))
        members = sorted(lease.device_key
                         for lease in sharded._kv_leases)
        if len(members) != 4:
            failures.append("sharded pool holds %d member leases "
                            "(want one per slice device)"
                            % len(members))
    finally:
        single.unload()
        sharded.unload()
    return failures


def run_once(attempt: int) -> tuple:
    from client_tpu.perf.bench_child import run_mesh_measure
    from client_tpu.server.app import build_core

    core = build_core([], warmup=False)
    try:
        result = run_mesh_measure(
            core, model_name="mesh_smoke_%d_" % attempt)
    finally:
        core.shutdown()
    print(json.dumps(result, indent=1))

    hard, soft = [], []
    if result.get("degrade_goodput_pct") != 100.0:
        hard.append("goodput %.2f%% with one chip killed (want "
                    "100%%: the sibling slice must mask every "
                    "failure)" % result.get("degrade_goodput_pct", 0.0))
    if result.get("ejections", 0) < 1:
        hard.append("no slice ejection recorded — the sick chip "
                    "never took its slice out of routing")
    if result.get("readmissions", 0) < 1:
        hard.append("no slice readmission recorded — the supervisor "
                    "never healed the ejected slice")
    if result.get("healthy_during_degrade") not in (None, 1):
        hard.append("%s slices healthy during the kill (want exactly "
                    "the sibling slice)"
                    % result.get("healthy_during_degrade"))
    scaling = result.get("scaling_2v1", 0.0)
    if scaling < 1.8:
        soft.append("throughput at 2 slices is %.2fx the 1-slice "
                    "rate (gate: 1.8x)" % scaling)
    return result, hard, soft


def main() -> int:
    failures = check_budget_proof()
    failures += check_llm_parity_and_sharded_kv()
    for failure in failures:
        print("FAIL: %s" % failure, file=sys.stderr)
    if failures:
        return 1
    print("mesh smoke: budget proof + golden parity + sharded paged "
          "KV passed")

    for attempt in range(2):
        result, hard, soft = run_once(attempt)
        for failure in hard:
            print("FAIL: %s" % failure, file=sys.stderr)
        if hard:
            return 1
        if not soft:
            print("mesh smoke passed: %.2fx scaling at 2 slices "
                  "(tp=%d), 100%% goodput through a killed chip "
                  "(%d ejection(s), %d readmission(s))"
                  % (result.get("scaling_2v1", 0.0),
                     result.get("slice_width", 0),
                     result.get("ejections", 0),
                     result.get("readmissions", 0)))
            return 0
        for failure in soft:
            print("attempt %d: %s" % (attempt, failure),
                  file=sys.stderr)
    print("FAIL: %s" % soft[0], file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
