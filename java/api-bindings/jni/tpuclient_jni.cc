// JNI shim for tpuclient.bindings.NativeClient: a handle-per-channel
// wrapper over the framework's own gRPC transport
// (native/library/grpc_transport.h). Calls exchange serialized
// ModelInferRequest/ModelInferResponse bytes, so no JNI-side proto
// marshalling is needed. Built as libtpuclientjni.so by the
// TPUCLIENT_JNI=ON CMake option (skipped when no JDK provides jni.h).
#include <jni.h>

#include <memory>
#include <string>

#include "grpc_transport.h"

namespace {

struct ClientHandle {
  std::shared_ptr<tpuclient::GrpcChannel> channel;
};

void ThrowRuntime(JNIEnv* env, const std::string& message) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, message.c_str());
}

std::string JavaBytes(JNIEnv* env, jbyteArray array) {
  jsize len = env->GetArrayLength(array);
  std::string out(static_cast<size_t>(len), '\0');
  env->GetByteArrayRegion(array, 0, len,
                          reinterpret_cast<jbyte*>(&out[0]));
  return out;
}

}  // namespace

extern "C" {

JNIEXPORT jlong JNICALL Java_tpuclient_bindings_NativeClient_create(
    JNIEnv* env, jclass, jstring url) {
  const char* chars = env->GetStringUTFChars(url, nullptr);
  std::string target(chars != nullptr ? chars : "");
  env->ReleaseStringUTFChars(url, chars);
  auto handle = std::make_unique<ClientHandle>();
  tpuclient::Error err =
      tpuclient::GrpcChannel::Create(&handle->channel, target);
  if (!err.IsOk()) return 0;
  return reinterpret_cast<jlong>(handle.release());
}

JNIEXPORT jbyteArray JNICALL Java_tpuclient_bindings_NativeClient_infer(
    JNIEnv* env, jclass, jlong handle, jbyteArray request) {
  if (request == nullptr) {
    jclass cls = env->FindClass("java/lang/NullPointerException");
    if (cls != nullptr) env->ThrowNew(cls, "request must not be null");
    return nullptr;
  }
  auto* client = reinterpret_cast<ClientHandle*>(handle);
  std::string response;
  tpuclient::Error err = client->channel->UnaryCall(
      "/inference.GRPCInferenceService/ModelInfer",
      JavaBytes(env, request), &response);
  if (!err.IsOk()) {
    ThrowRuntime(env, err.Message());
    return nullptr;
  }
  jbyteArray out = env->NewByteArray(static_cast<jsize>(response.size()));
  if (out != nullptr) {
    env->SetByteArrayRegion(
        out, 0, static_cast<jsize>(response.size()),
        reinterpret_cast<const jbyte*>(response.data()));
  }
  return out;
}

JNIEXPORT jboolean JNICALL Java_tpuclient_bindings_NativeClient_isServerLive(
    JNIEnv* env, jclass, jlong handle) {
  auto* client = reinterpret_cast<ClientHandle*>(handle);
  std::string response;
  tpuclient::Error err = client->channel->UnaryCall(
      "/inference.GRPCInferenceService/ServerLive", "", &response);
  // ServerLiveResponse{live=true} encodes as {0x08, 0x01}.
  return (err.IsOk() && response.size() >= 2 &&
          static_cast<uint8_t>(response[0]) == 0x08 && response[1] == 1)
             ? JNI_TRUE
             : JNI_FALSE;
}

JNIEXPORT void JNICALL Java_tpuclient_bindings_NativeClient_destroy(
    JNIEnv*, jclass, jlong handle) {
  delete reinterpret_cast<ClientHandle*>(handle);
}

}  // extern "C"
