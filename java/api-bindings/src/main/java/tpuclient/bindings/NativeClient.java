package tpuclient.bindings;

/**
 * JNI surface over the framework's C++ gRPC client
 * (native/library/grpc_client.h, built as libtpugrpcclient.so with
 * the JNI shim from java/api-bindings/jni/tpuclient_jni.cc).
 *
 * The exchange format is serialized ModelInferRequest /
 * ModelInferResponse protobufs — the same bytes-in/bytes-out contract
 * as the embedded-core surface — so this class carries no
 * tensor-marshalling logic of its own; pair it with the wire codecs
 * in the pure-Java client (java/src/main/java/tpuclient).
 *
 * Analogue of the reference's java-api-bindings (JavaCPP presets over
 * the tritonserver C API).
 */
public final class NativeClient implements AutoCloseable {
  static {
    System.loadLibrary("tpuclientjni");
  }

  private long handle;

  public NativeClient(String url) {
    handle = create(url);
    if (handle == 0) {
      throw new IllegalStateException("failed to connect to " + url);
    }
  }

  /** Serialized ModelInferRequest in, serialized ModelInferResponse
   *  out; throws RuntimeException with the server's error text. */
  public byte[] infer(byte[] requestProto) {
    ensureOpen();
    return infer(handle, requestProto);
  }

  public boolean isServerLive() {
    ensureOpen();
    return isServerLive(handle);
  }

  @Override
  public void close() {
    if (handle != 0) {
      destroy(handle);
      handle = 0;
    }
  }

  private void ensureOpen() {
    if (handle == 0) {
      throw new IllegalStateException("client is closed");
    }
  }

  private static native long create(String url);

  private static native byte[] infer(long handle, byte[] requestProto);

  private static native boolean isServerLive(long handle);

  private static native void destroy(long handle);
}
