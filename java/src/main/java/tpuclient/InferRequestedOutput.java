// A requested output tensor (parity: reference
// triton/client/InferRequestedOutput.java).
package tpuclient;

import java.util.LinkedHashMap;
import java.util.Map;

public class InferRequestedOutput {
  private final String name;
  private final boolean binaryData;
  private final int classCount;
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferRequestedOutput(String name) { this(name, true, 0); }

  public InferRequestedOutput(String name, boolean binaryData) {
    this(name, binaryData, 0);
  }

  public InferRequestedOutput(String name, boolean binaryData,
                              int classCount) {
    this.name = name;
    this.binaryData = binaryData;
    this.classCount = classCount;
  }

  public String getName() { return name; }

  public void setSharedMemory(String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
  }

  Map<String, Object> toJsonEntry() {
    Map<String, Object> entry = new LinkedHashMap<>();
    entry.put("name", name);
    Map<String, Object> parameters = new LinkedHashMap<>();
    if (sharedMemoryRegion != null) {
      parameters.put("shared_memory_region", sharedMemoryRegion);
      parameters.put("shared_memory_byte_size", sharedMemoryByteSize);
      if (sharedMemoryOffset != 0) {
        parameters.put("shared_memory_offset", sharedMemoryOffset);
      }
    } else {
      parameters.put("binary_data", binaryData);
    }
    if (classCount > 0) {
      parameters.put("classification", classCount);
    }
    entry.put("parameters", parameters);
    return entry;
  }
}
