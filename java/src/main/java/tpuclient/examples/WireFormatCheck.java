package tpuclient.examples;

import java.util.Base64;
import java.util.List;

import tpuclient.DataType;
import tpuclient.InferInput;
import tpuclient.InferRequestedOutput;
import tpuclient.InferenceServerClient;

/**
 * Wire-format conformance probe: assembles the canonical "simple"
 * request (the same tensors tests/test_java_source.py builds with the
 * Python client) and prints the binary-protocol body, so the test can
 * assert the Java client's bytes match the Python client's.
 *
 * Output: two lines — the JSON header length, then the base64 body.
 */
public final class WireFormatCheck {
  private WireFormatCheck() {}

  public static void main(String[] args) throws Exception {
    int[] values0 = new int[16];
    int[] values1 = new int[16];
    for (int i = 0; i < 16; i++) {
      values0[i] = i;
      values1[i] = 1;
    }
    InferInput input0 = new InferInput(
        "INPUT0", new long[] {16}, DataType.INT32);
    input0.setData(values0);
    InferInput input1 = new InferInput(
        "INPUT1", new long[] {16}, DataType.INT32);
    input1.setData(values1);
    InferRequestedOutput output0 = new InferRequestedOutput("OUTPUT0", true);
    InferRequestedOutput output1 = new InferRequestedOutput("OUTPUT1", true);

    InferenceServerClient.WireBody wire =
        InferenceServerClient.buildInferBody(
            List.of(input0, input1), List.of(output0, output1));
    System.out.println(wire.headerLength);
    System.out.println(Base64.getEncoder().encodeToString(wire.body));
  }
}
