// Minimal sync inference against the `simple` add/sub model (parity
// example: reference triton/client/examples/SimpleInferClient.java).
//
// Usage: java tpuclient.examples.SimpleInferClient [host:port]
package tpuclient.examples;

import java.util.List;
import tpuclient.DataType;
import tpuclient.InferInput;
import tpuclient.InferRequestedOutput;
import tpuclient.InferResult;
import tpuclient.InferenceServerClient;

public class SimpleInferClient {
  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    try (InferenceServerClient client = new InferenceServerClient(url)) {
      if (!client.isServerLive()) {
        System.err.println("server is not live");
        System.exit(1);
      }

      int[] in0 = new int[16];
      int[] in1 = new int[16];
      for (int i = 0; i < 16; i++) {
        in0[i] = i;
        in1[i] = 1;
      }
      InferInput input0 =
          new InferInput("INPUT0", new long[] {16}, DataType.INT32);
      InferInput input1 =
          new InferInput("INPUT1", new long[] {16}, DataType.INT32);
      input0.setData(in0);
      input1.setData(in1);

      InferResult result = client.infer(
          "simple", List.of(input0, input1),
          List.of(new InferRequestedOutput("OUTPUT0"),
                  new InferRequestedOutput("OUTPUT1")));

      int[] sum = result.getOutputAsInt("OUTPUT0");
      int[] diff = result.getOutputAsInt("OUTPUT1");
      for (int i = 0; i < 16; i++) {
        System.out.println(in0[i] + " + " + in1[i] + " = " + sum[i]);
        if (sum[i] != in0[i] + in1[i] || diff[i] != in0[i] - in1[i]) {
          System.err.println("mismatch at " + i);
          System.exit(1);
        }
      }
      System.out.println("PASS: infer");
    }
  }
}
