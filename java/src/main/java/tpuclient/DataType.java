// KServe-v2 tensor datatypes (parity: the reference Java client's
// pojo/DataType.java — /root/reference/src/java/src/main/java/triton/
// client/pojo/DataType.java — re-keyed for the TPU server's type set
// including BF16).
package tpuclient;

public enum DataType {
  BOOL(1), UINT8(1), UINT16(2), UINT32(4), UINT64(8),
  INT8(1), INT16(2), INT32(4), INT64(8),
  FP16(2), BF16(2), FP32(4), FP64(8), BYTES(0);

  private final int byteSize;

  DataType(int byteSize) { this.byteSize = byteSize; }

  /** Bytes per element; 0 for variable-size BYTES. */
  public int byteSize() { return byteSize; }
}
