// Minimal JSON reader/writer so the client has zero third-party
// dependencies (the reference Java client pulls Jackson; this image's
// build environment is offline, so the subset of JSON the KServe-v2
// protocol needs — objects, arrays, strings, numbers, booleans — is
// implemented here).
package tpuclient;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public final class Json {

  private Json() {}

  /** Parses a JSON document into Map/List/String/Double/Boolean/null. */
  public static Object parse(String text) throws InferenceException {
    Parser parser = new Parser(text);
    Object value = parser.parseValue();
    parser.skipWhitespace();
    if (!parser.atEnd()) {
      throw new InferenceException("trailing JSON content");
    }
    return value;
  }

  @SuppressWarnings("unchecked")
  public static Map<String, Object> parseObject(String text)
      throws InferenceException {
    Object value = parse(text);
    if (!(value instanceof Map)) {
      throw new InferenceException("expected JSON object");
    }
    return (Map<String, Object>) value;
  }

  /** Serializes Map/List/String/Number/Boolean/null back to JSON. */
  public static String write(Object value) {
    StringBuilder sb = new StringBuilder();
    writeValue(value, sb);
    return sb.toString();
  }

  private static void writeValue(Object value, StringBuilder sb) {
    if (value == null) {
      sb.append("null");
    } else if (value instanceof String) {
      writeString((String) value, sb);
    } else if (value instanceof Boolean) {
      sb.append(value.toString());
    } else if (value instanceof Double || value instanceof Float) {
      double d = ((Number) value).doubleValue();
      if (d == Math.floor(d) && !Double.isInfinite(d)) {
        sb.append((long) d);
      } else {
        sb.append(d);
      }
    } else if (value instanceof Number) {
      sb.append(value.toString());
    } else if (value instanceof Map) {
      sb.append('{');
      boolean first = true;
      for (Map.Entry<?, ?> e : ((Map<?, ?>) value).entrySet()) {
        if (!first) sb.append(',');
        first = false;
        writeString(e.getKey().toString(), sb);
        sb.append(':');
        writeValue(e.getValue(), sb);
      }
      sb.append('}');
    } else if (value instanceof List) {
      sb.append('[');
      boolean first = true;
      for (Object item : (List<?>) value) {
        if (!first) sb.append(',');
        first = false;
        writeValue(item, sb);
      }
      sb.append(']');
    } else {
      writeString(value.toString(), sb);
    }
  }

  private static void writeString(String s, StringBuilder sb) {
    sb.append('"');
    for (int i = 0; i < s.length(); i++) {
      char c = s.charAt(i);
      switch (c) {
        case '"': sb.append("\\\""); break;
        case '\\': sb.append("\\\\"); break;
        case '\n': sb.append("\\n"); break;
        case '\r': sb.append("\\r"); break;
        case '\t': sb.append("\\t"); break;
        default:
          if (c < 0x20) {
            sb.append(String.format("\\u%04x", (int) c));
          } else {
            sb.append(c);
          }
      }
    }
    sb.append('"');
  }

  private static final class Parser {
    private final String text;
    private int pos = 0;

    Parser(String text) { this.text = text; }

    boolean atEnd() { return pos >= text.length(); }

    void skipWhitespace() {
      while (pos < text.length() && Character.isWhitespace(text.charAt(pos))) {
        pos++;
      }
    }

    Object parseValue() throws InferenceException {
      skipWhitespace();
      if (atEnd()) throw new InferenceException("unexpected end of JSON");
      char c = text.charAt(pos);
      switch (c) {
        case '{': return parseObjectBody();
        case '[': return parseArrayBody();
        case '"': return parseString();
        case 't': expect("true"); return Boolean.TRUE;
        case 'f': expect("false"); return Boolean.FALSE;
        case 'n': expect("null"); return null;
        default: return parseNumber();
      }
    }

    private void expect(String literal) throws InferenceException {
      if (!text.startsWith(literal, pos)) {
        throw new InferenceException("bad JSON literal at " + pos);
      }
      pos += literal.length();
    }

    private Map<String, Object> parseObjectBody() throws InferenceException {
      Map<String, Object> map = new LinkedHashMap<>();
      pos++;  // '{'
      skipWhitespace();
      if (!atEnd() && text.charAt(pos) == '}') { pos++; return map; }
      while (true) {
        skipWhitespace();
        String key = parseString();
        skipWhitespace();
        if (atEnd() || text.charAt(pos) != ':') {
          throw new InferenceException("expected ':' at " + pos);
        }
        pos++;
        map.put(key, parseValue());
        skipWhitespace();
        if (atEnd()) throw new InferenceException("unterminated object");
        char c = text.charAt(pos++);
        if (c == '}') return map;
        if (c != ',') throw new InferenceException("expected ',' at " + pos);
      }
    }

    private List<Object> parseArrayBody() throws InferenceException {
      List<Object> list = new ArrayList<>();
      pos++;  // '['
      skipWhitespace();
      if (!atEnd() && text.charAt(pos) == ']') { pos++; return list; }
      while (true) {
        list.add(parseValue());
        skipWhitespace();
        if (atEnd()) throw new InferenceException("unterminated array");
        char c = text.charAt(pos++);
        if (c == ']') return list;
        if (c != ',') throw new InferenceException("expected ',' at " + pos);
      }
    }

    private String parseString() throws InferenceException {
      if (atEnd() || text.charAt(pos) != '"') {
        throw new InferenceException("expected string at " + pos);
      }
      pos++;
      StringBuilder sb = new StringBuilder();
      while (true) {
        if (atEnd()) throw new InferenceException("unterminated string");
        char c = text.charAt(pos++);
        if (c == '"') return sb.toString();
        if (c == '\\') {
          if (atEnd()) throw new InferenceException("bad escape");
          char e = text.charAt(pos++);
          switch (e) {
            case '"': sb.append('"'); break;
            case '\\': sb.append('\\'); break;
            case '/': sb.append('/'); break;
            case 'b': sb.append('\b'); break;
            case 'f': sb.append('\f'); break;
            case 'n': sb.append('\n'); break;
            case 'r': sb.append('\r'); break;
            case 't': sb.append('\t'); break;
            case 'u':
              if (pos + 4 > text.length()) {
                throw new InferenceException("bad unicode escape");
              }
              sb.append((char) Integer.parseInt(
                  text.substring(pos, pos + 4), 16));
              pos += 4;
              break;
            default:
              throw new InferenceException("bad escape '\\" + e + "'");
          }
        } else {
          sb.append(c);
        }
      }
    }

    private Double parseNumber() throws InferenceException {
      int start = pos;
      while (pos < text.length()
          && "+-0123456789.eE".indexOf(text.charAt(pos)) >= 0) {
        pos++;
      }
      try {
        return Double.parseDouble(text.substring(start, pos));
      } catch (NumberFormatException e) {
        throw new InferenceException("bad JSON number at " + start);
      }
    }
  }
}
