// Parsed inference response: JSON header + trailing binary segments
// (parity: reference triton/client/InferResult.java +
// BinaryProtocol.java).
package tpuclient;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public class InferResult {
  private final Map<String, Object> header;
  private final Map<String, byte[]> binaryOutputs = new LinkedHashMap<>();
  private final Map<String, Map<String, Object>> outputEntries =
      new LinkedHashMap<>();

  @SuppressWarnings("unchecked")
  InferResult(byte[] body, int headerLength) throws InferenceException {
    int jsonEnd = headerLength > 0 ? headerLength : body.length;
    if (jsonEnd > body.length) {
      throw new InferenceException("response header length exceeds body");
    }
    header = Json.parseObject(
        new String(body, 0, jsonEnd, StandardCharsets.UTF_8));
    int offset = jsonEnd;
    Object outputs = header.get("outputs");
    if (outputs instanceof List) {
      for (Object entryObj : (List<Object>) outputs) {
        Map<String, Object> entry = (Map<String, Object>) entryObj;
        String name = (String) entry.get("name");
        outputEntries.put(name, entry);
        Object params = entry.get("parameters");
        if (params instanceof Map) {
          Object sizeObj = ((Map<String, Object>) params)
              .get("binary_data_size");
          if (sizeObj instanceof Number) {
            int size = ((Number) sizeObj).intValue();
            if (offset + size > body.length) {
              throw new InferenceException(
                  "binary output '" + name + "' truncated");
            }
            byte[] raw = new byte[size];
            System.arraycopy(body, offset, raw, 0, size);
            binaryOutputs.put(name, raw);
            offset += size;
          }
        }
      }
    }
  }

  public String getModelName() {
    Object name = header.get("model_name");
    return name == null ? "" : name.toString();
  }

  public String getId() {
    Object id = header.get("id");
    return id == null ? "" : id.toString();
  }

  @SuppressWarnings("unchecked")
  public long[] getShape(String outputName) throws InferenceException {
    Map<String, Object> entry = requireOutput(outputName);
    List<Object> dims = (List<Object>) entry.get("shape");
    long[] shape = new long[dims.size()];
    for (int i = 0; i < shape.length; i++) {
      shape[i] = ((Number) dims.get(i)).longValue();
    }
    return shape;
  }

  public DataType getDataType(String outputName) throws InferenceException {
    Map<String, Object> entry = requireOutput(outputName);
    return DataType.valueOf(entry.get("datatype").toString());
  }

  /** Raw little-endian bytes of a binary output. */
  public byte[] getOutputData(String outputName) throws InferenceException {
    byte[] raw = binaryOutputs.get(outputName);
    if (raw == null) {
      throw new InferenceException(
          "output '" + outputName + "' has no binary data");
    }
    return raw;
  }

  public int[] getOutputAsInt(String outputName) throws InferenceException {
    ByteBuffer buffer = bufferFor(outputName);
    int[] out = new int[buffer.remaining() / 4];
    buffer.asIntBuffer().get(out);
    return out;
  }

  public long[] getOutputAsLong(String outputName) throws InferenceException {
    ByteBuffer buffer = bufferFor(outputName);
    long[] out = new long[buffer.remaining() / 8];
    buffer.asLongBuffer().get(out);
    return out;
  }

  public float[] getOutputAsFloat(String outputName)
      throws InferenceException {
    ByteBuffer buffer = bufferFor(outputName);
    float[] out = new float[buffer.remaining() / 4];
    buffer.asFloatBuffer().get(out);
    return out;
  }

  public double[] getOutputAsDouble(String outputName)
      throws InferenceException {
    ByteBuffer buffer = bufferFor(outputName);
    double[] out = new double[buffer.remaining() / 8];
    buffer.asDoubleBuffer().get(out);
    return out;
  }

  /** BYTES tensor decode: 4-byte-LE length-prefixed strings. */
  public List<String> getOutputAsStrings(String outputName)
      throws InferenceException {
    byte[] raw = getOutputData(outputName);
    ByteBuffer buffer = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    List<String> out = new ArrayList<>();
    while (buffer.remaining() >= 4) {
      int len = buffer.getInt();
      if (len < 0 || len > buffer.remaining()) {
        throw new InferenceException("malformed BYTES tensor");
      }
      byte[] s = new byte[len];
      buffer.get(s);
      out.add(new String(s, StandardCharsets.UTF_8));
    }
    return out;
  }

  private ByteBuffer bufferFor(String outputName) throws InferenceException {
    return ByteBuffer.wrap(getOutputData(outputName))
        .order(ByteOrder.LITTLE_ENDIAN);
  }

  private Map<String, Object> requireOutput(String outputName)
      throws InferenceException {
    Map<String, Object> entry = outputEntries.get(outputName);
    if (entry == null) {
      throw new InferenceException(
          "response has no output '" + outputName + "'");
    }
    return entry;
  }
}
