// Endpoint abstraction: where the client sends each request (parity:
// the reference's triton/client/endpoint/AbstractEndpoint.java, which
// lets discovery-backed strategies hand out addresses per request).
package tpuclient.endpoint;

import tpuclient.InferenceException;

/**
 * Supplies a "host:port[/path]" address for each outgoing request.
 * Implementations may rotate over multiple serving hosts (the
 * multi-host TPU serving case) or resolve dynamically from a
 * discovery service; {@code next()} is called once per request, so a
 * retry after a transport failure naturally lands on the next
 * address.
 */
public abstract class AbstractEndpoint {
  /** Next address to use, in host:port[/path] form (no scheme). */
  public abstract String next() throws InferenceException;

  /** Number of distinct addresses behind this endpoint (>= 1). */
  public abstract int size() throws InferenceException;
}
