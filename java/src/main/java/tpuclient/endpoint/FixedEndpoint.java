// Single-address endpoint (parity: the reference's
// triton/client/endpoint/FixedEndpoint.java).
package tpuclient.endpoint;

import tpuclient.InferenceException;

/** Endpoint pinned to one address. */
public class FixedEndpoint extends AbstractEndpoint {
  private final String address;

  /** address is "host:port[/path]" without a scheme. */
  public FixedEndpoint(String address) {
    if (address == null || address.isEmpty()) {
      throw new IllegalArgumentException("address must not be empty");
    }
    if (address.contains("://")) {
      throw new IllegalArgumentException(
          "address must be host:port[/path] without a scheme");
    }
    this.address = address;
  }

  @Override
  public String next() {
    return address;
  }

  @Override
  public int size() {
    return 1;
  }
}
