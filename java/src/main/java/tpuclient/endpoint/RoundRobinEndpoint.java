// Rotating multi-address endpoint: each request (and each retry) goes
// to the next serving host — the client-side face of multi-host TPU
// serving (the reference's AbstractEndpoint exists for exactly this
// kind of strategy; it ships only the fixed one).
package tpuclient.endpoint;

import java.util.ArrayList;
import java.util.List;
import java.util.concurrent.atomic.AtomicInteger;

/** Endpoint cycling over a fixed list of addresses. */
public class RoundRobinEndpoint extends AbstractEndpoint {
  private final List<String> addresses;
  private final AtomicInteger cursor = new AtomicInteger();

  /** addresses are "host:port[/path]" without schemes. */
  public RoundRobinEndpoint(List<String> addresses) {
    if (addresses == null || addresses.isEmpty()) {
      throw new IllegalArgumentException("need at least one address");
    }
    for (String address : addresses) {
      if (address.contains("://")) {
        throw new IllegalArgumentException(
            "addresses must be host:port[/path] without a scheme");
      }
    }
    this.addresses = new ArrayList<>(addresses);
  }

  @Override
  public String next() {
    int index = Math.floorMod(cursor.getAndIncrement(), addresses.size());
    return addresses.get(index);
  }

  @Override
  public int size() {
    return addresses.size();
  }
}
