// Error raised by every client API (parity: reference
// triton/client/InferenceException.java).
package tpuclient;

public class InferenceException extends Exception {
  public InferenceException(String message) { super(message); }

  public InferenceException(String message, Throwable cause) {
    super(message, cause);
  }
}
