// An input tensor for an inference request (parity: reference
// triton/client/InferInput.java): typed setters serialize into the
// binary protocol's little-endian layout, BYTES tensors are 4-byte-LE
// length-prefixed, and setSharedMemory routes through a registered
// region (system shm or the TPU HBM arena).
package tpuclient;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.LinkedHashMap;
import java.util.Map;

public class InferInput {
  private final String name;
  private final long[] shape;
  private final DataType dataType;
  private byte[] data;
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferInput(String name, long[] shape, DataType dataType) {
    this.name = name;
    this.shape = shape.clone();
    this.dataType = dataType;
  }

  public String getName() { return name; }

  public long[] getShape() { return shape.clone(); }

  public DataType getDataType() { return dataType; }

  /** Raw binary payload for the binary protocol, or null if in shm. */
  public byte[] getData() { return data; }

  public boolean isSharedMemory() { return sharedMemoryRegion != null; }

  public void setSharedMemory(String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
    this.data = null;
  }

  private ByteBuffer allocate(int elements, int elementSize) {
    return ByteBuffer.allocate(elements * elementSize)
        .order(ByteOrder.LITTLE_ENDIAN);
  }

  public void setData(int[] values) throws InferenceException {
    requireType(DataType.INT32, DataType.UINT32);
    ByteBuffer buffer = allocate(values.length, 4);
    for (int v : values) buffer.putInt(v);
    data = buffer.array();
  }

  public void setData(long[] values) throws InferenceException {
    requireType(DataType.INT64, DataType.UINT64);
    ByteBuffer buffer = allocate(values.length, 8);
    for (long v : values) buffer.putLong(v);
    data = buffer.array();
  }

  public void setData(float[] values) throws InferenceException {
    requireType(DataType.FP32);
    ByteBuffer buffer = allocate(values.length, 4);
    for (float v : values) buffer.putFloat(v);
    data = buffer.array();
  }

  public void setData(double[] values) throws InferenceException {
    requireType(DataType.FP64);
    ByteBuffer buffer = allocate(values.length, 8);
    for (double v : values) buffer.putDouble(v);
    data = buffer.array();
  }

  public void setData(boolean[] values) throws InferenceException {
    requireType(DataType.BOOL);
    byte[] out = new byte[values.length];
    for (int i = 0; i < values.length; i++) {
      out[i] = (byte) (values[i] ? 1 : 0);
    }
    data = out;
  }

  public void setData(byte[] rawBytes) {
    data = rawBytes.clone();
  }

  /** BYTES tensor: each string serialized with a 4-byte LE prefix. */
  public void setData(String[] values) throws InferenceException {
    requireType(DataType.BYTES);
    int total = 0;
    byte[][] encoded = new byte[values.length][];
    for (int i = 0; i < values.length; i++) {
      encoded[i] = values[i].getBytes(StandardCharsets.UTF_8);
      total += 4 + encoded[i].length;
    }
    ByteBuffer buffer =
        ByteBuffer.allocate(total).order(ByteOrder.LITTLE_ENDIAN);
    for (byte[] e : encoded) {
      buffer.putInt(e.length);
      buffer.put(e);
    }
    data = buffer.array();
  }

  private void requireType(DataType... allowed) throws InferenceException {
    for (DataType t : allowed) {
      if (dataType == t) return;
    }
    throw new InferenceException(
        "input '" + name + "' has datatype " + dataType);
  }

  /** The "inputs" entry for the request's JSON header. */
  Map<String, Object> toJsonEntry() {
    Map<String, Object> entry = new LinkedHashMap<>();
    entry.put("name", name);
    java.util.List<Object> dims = new java.util.ArrayList<>();
    for (long d : shape) dims.add(d);
    entry.put("shape", dims);
    entry.put("datatype", dataType.name());
    Map<String, Object> parameters = new LinkedHashMap<>();
    if (isSharedMemory()) {
      parameters.put("shared_memory_region", sharedMemoryRegion);
      parameters.put("shared_memory_byte_size", sharedMemoryByteSize);
      if (sharedMemoryOffset != 0) {
        parameters.put("shared_memory_offset", sharedMemoryOffset);
      }
    } else {
      parameters.put("binary_data_size", data == null ? 0 : data.length);
    }
    entry.put("parameters", parameters);
    return entry;
  }
}
