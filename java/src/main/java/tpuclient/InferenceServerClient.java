// HTTP/REST (KServe-v2) client for the TPU inference server (parity:
// the reference Java client, triton/client/InferenceServerClient.java
// — HTTP-only, binary tensor protocol, sync + CompletableFuture
// async, health/metadata/model-control/shared-memory verbs). Built on
// java.net.http (JDK 11+), no third-party dependencies; the CUDA shm
// verbs are replaced by TPU HBM arena verbs carrying the serialized
// region descriptor.
package tpuclient;

import java.io.IOException;
import java.net.URI;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.ByteBuffer;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.ArrayList;
import java.util.Base64;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import java.util.concurrent.CompletableFuture;

import tpuclient.endpoint.AbstractEndpoint;
import tpuclient.endpoint.FixedEndpoint;

public class InferenceServerClient implements AutoCloseable {
  private final AbstractEndpoint endpoint;
  private final HttpClient http;
  private final Duration requestTimeout;
  // Connection-level failures on the synchronous infer() path retry
  // up to retryCnt additional attempts; each attempt re-resolves the
  // endpoint, so multi-address endpoints fail over naturally (parity:
  // InferenceServerClient.java:245,293). Timeouts do NOT retry — the
  // server may already be executing the request — and asyncInfer()
  // is single-attempt like the reference's async path.
  private volatile int retryCnt = 3;

  /** url is "host:port" (no scheme), like the reference. */
  public InferenceServerClient(String url) {
    this(url, Duration.ofSeconds(30), Duration.ofSeconds(60));
  }

  public InferenceServerClient(String url, Duration connectTimeout,
                               Duration requestTimeout) {
    this(new FixedEndpoint(url), connectTimeout, requestTimeout);
  }

  public InferenceServerClient(AbstractEndpoint endpoint) {
    this(endpoint, Duration.ofSeconds(30), Duration.ofSeconds(60));
  }

  public InferenceServerClient(AbstractEndpoint endpoint,
                               Duration connectTimeout,
                               Duration requestTimeout) {
    this.endpoint = endpoint;
    this.requestTimeout = requestTimeout;
    this.http = HttpClient.newBuilder()
        .version(HttpClient.Version.HTTP_1_1)
        .connectTimeout(connectTimeout)
        .build();
  }

  /** Extra attempts after a transport failure (0 = fail fast). */
  public void setRetryCnt(int retryCnt) {
    if (retryCnt < 0) {
      throw new IllegalArgumentException("retryCnt must be >= 0");
    }
    this.retryCnt = retryCnt;
  }

  private String baseUrl() throws InferenceException {
    return "http://" + endpoint.next();
  }

  @Override
  public void close() {}

  // -- health / metadata -------------------------------------------------

  public boolean isServerLive() throws InferenceException {
    return getStatus("/v2/health/live") == 200;
  }

  public boolean isServerReady() throws InferenceException {
    return getStatus("/v2/health/ready") == 200;
  }

  public boolean isModelReady(String modelName) throws InferenceException {
    return getStatus("/v2/models/" + modelName + "/ready") == 200;
  }

  public Map<String, Object> getServerMetadata() throws InferenceException {
    return Json.parseObject(get("/v2"));
  }

  public Map<String, Object> getModelMetadata(String modelName)
      throws InferenceException {
    return Json.parseObject(get("/v2/models/" + modelName));
  }

  public Map<String, Object> getModelConfig(String modelName)
      throws InferenceException {
    return Json.parseObject(get("/v2/models/" + modelName + "/config"));
  }

  public Map<String, Object> getInferenceStatistics(String modelName)
      throws InferenceException {
    return Json.parseObject(get("/v2/models/" + modelName + "/stats"));
  }

  // -- model control ----------------------------------------------------

  public void loadModel(String modelName) throws InferenceException {
    post("/v2/repository/models/" + modelName + "/load", "{}");
  }

  public void unloadModel(String modelName) throws InferenceException {
    post("/v2/repository/models/" + modelName + "/unload", "{}");
  }

  // -- shared memory ----------------------------------------------------

  public void registerSystemSharedMemory(String name, String key,
                                         long byteSize)
      throws InferenceException {
    Map<String, Object> body = new LinkedHashMap<>();
    body.put("key", key);
    body.put("offset", 0);
    body.put("byte_size", byteSize);
    post("/v2/systemsharedmemory/region/" + name + "/register",
         Json.write(body));
  }

  public void unregisterSystemSharedMemory(String name)
      throws InferenceException {
    String path = name.isEmpty()
        ? "/v2/systemsharedmemory/unregister"
        : "/v2/systemsharedmemory/region/" + name + "/unregister";
    post(path, "{}");
  }

  /**
   * Registers a TPU HBM arena region (the slot the reference fills
   * with a base64 cudaIpcMemHandle_t; here rawHandle is the arena's
   * serialized region descriptor).
   */
  public void registerTpuSharedMemory(String name, byte[] rawHandle,
                                      long deviceId, long byteSize)
      throws InferenceException {
    Map<String, Object> handle = new LinkedHashMap<>();
    handle.put("b64", Base64.getEncoder().encodeToString(rawHandle));
    Map<String, Object> body = new LinkedHashMap<>();
    body.put("raw_handle", handle);
    body.put("device_id", deviceId);
    body.put("byte_size", byteSize);
    post("/v2/tpusharedmemory/region/" + name + "/register",
         Json.write(body));
  }

  public void unregisterTpuSharedMemory(String name)
      throws InferenceException {
    String path = name.isEmpty()
        ? "/v2/tpusharedmemory/unregister"
        : "/v2/tpusharedmemory/region/" + name + "/unregister";
    post(path, "{}");
  }

  // -- inference --------------------------------------------------------

  public InferResult infer(String modelName, List<InferInput> inputs,
                           List<InferRequestedOutput> outputs)
      throws InferenceException {
    WireBody wire = buildInferBody(inputs, outputs);
    // Bounded retry on transport failures; the request is rebuilt per
    // attempt so a rotating endpoint fails over to the next host.
    for (int attempt = 0; ; attempt++) {
      HttpRequest request = buildInferRequest(modelName, wire);
      try {
        HttpResponse<byte[]> response =
            http.send(request, HttpResponse.BodyHandlers.ofByteArray());
        return parseInferResponse(response);
      } catch (InterruptedException e) {
        Thread.currentThread().interrupt();
        throw new InferenceException("infer request interrupted", e);
      } catch (java.net.http.HttpConnectTimeoutException e) {
        // No request was sent: connect timeouts are safe to retry
        // (and the failover case RoundRobinEndpoint exists for).
        if (attempt >= retryCnt) {
          throw new InferenceException(
              "infer failed after " + (attempt + 1) + " attempt(s), url: "
              + request.uri(), e);
        }
      } catch (java.net.http.HttpTimeoutException e) {
        // The server may already be executing this non-idempotent
        // request: a retry would duplicate the inference.
        throw new InferenceException(
            "infer timed out, url: " + request.uri(), e);
      } catch (IOException e) {
        if (attempt >= retryCnt) {
          throw new InferenceException(
              "infer failed after " + (attempt + 1) + " attempt(s), url: "
              + request.uri(), e);
        }
      }
    }
  }

  /** Async variant resolved on the HttpClient's executor. */
  public CompletableFuture<InferResult> asyncInfer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) throws InferenceException {
    HttpRequest request =
        buildInferRequest(modelName, buildInferBody(inputs, outputs));
    return http.sendAsync(request, HttpResponse.BodyHandlers.ofByteArray())
        .thenApply(response -> {
          try {
            return parseInferResponse(response);
          } catch (InferenceException e) {
            throw new RuntimeException(e);
          }
        });
  }

  /** The assembled binary-protocol request body. */
  public static final class WireBody {
    public final byte[] body;
    public final int headerLength;

    WireBody(byte[] body, int headerLength) {
      this.body = body;
      this.headerLength = headerLength;
    }
  }

  /**
   * Builds the v2 binary-protocol body (JSON header + concatenated
   * raw tensor segments). Exposed statically so wire-format
   * conformance checks can compare these bytes against the Python
   * client's generate_request_body output.
   */
  public static WireBody buildInferBody(
      List<InferInput> inputs, List<InferRequestedOutput> outputs)
      throws InferenceException {
    Map<String, Object> header = new LinkedHashMap<>();
    List<Object> inputEntries = new ArrayList<>();
    List<byte[]> binarySegments = new ArrayList<>();
    for (InferInput input : inputs) {
      inputEntries.add(input.toJsonEntry());
      if (!input.isSharedMemory()) {
        byte[] data = input.getData();
        if (data == null) {
          throw new InferenceException(
              "input '" + input.getName() + "' has no data");
        }
        binarySegments.add(data);
      }
    }
    header.put("inputs", inputEntries);
    if (outputs != null && !outputs.isEmpty()) {
      List<Object> outputEntries = new ArrayList<>();
      for (InferRequestedOutput output : outputs) {
        outputEntries.add(output.toJsonEntry());
      }
      header.put("outputs", outputEntries);
    }

    byte[] headerBytes = Json.write(header).getBytes(StandardCharsets.UTF_8);
    int total = headerBytes.length;
    for (byte[] segment : binarySegments) total += segment.length;
    ByteBuffer body = ByteBuffer.allocate(total);
    body.put(headerBytes);
    for (byte[] segment : binarySegments) body.put(segment);
    return new WireBody(body.array(), headerBytes.length);
  }

  private HttpRequest buildInferRequest(String modelName, WireBody wire)
      throws InferenceException {
    return HttpRequest.newBuilder()
        .uri(URI.create(baseUrl() + "/v2/models/" + modelName + "/infer"))
        .timeout(requestTimeout)
        .header("Content-Type", "application/octet-stream")
        .header("Inference-Header-Content-Length",
                Integer.toString(wire.headerLength))
        .POST(HttpRequest.BodyPublishers.ofByteArray(wire.body))
        .build();
  }

  private InferResult parseInferResponse(HttpResponse<byte[]> response)
      throws InferenceException {
    if (response.statusCode() != 200) {
      throw new InferenceException(
          "HTTP " + response.statusCode() + ": "
          + new String(response.body(), StandardCharsets.UTF_8));
    }
    int headerLength = response.headers()
        .firstValue("Inference-Header-Content-Length")
        .map(Integer::parseInt)
        .orElse(0);
    return new InferResult(response.body(), headerLength);
  }

  // -- transport helpers -------------------------------------------------

  private int getStatus(String path) throws InferenceException {
    try {
      HttpRequest request = HttpRequest.newBuilder()
          .uri(URI.create(baseUrl() + path))
          .timeout(requestTimeout)
          .GET()
          .build();
      return http.send(request, HttpResponse.BodyHandlers.discarding())
          .statusCode();
    } catch (IOException | InterruptedException e) {
      throw new InferenceException("request failed: " + path, e);
    }
  }

  private String get(String path) throws InferenceException {
    try {
      HttpRequest request = HttpRequest.newBuilder()
          .uri(URI.create(baseUrl() + path))
          .timeout(requestTimeout)
          .GET()
          .build();
      HttpResponse<String> response =
          http.send(request, HttpResponse.BodyHandlers.ofString());
      if (response.statusCode() != 200) {
        throw new InferenceException(
            "HTTP " + response.statusCode() + ": " + response.body());
      }
      return response.body();
    } catch (IOException | InterruptedException e) {
      throw new InferenceException("request failed: " + path, e);
    }
  }

  private String post(String path, String body) throws InferenceException {
    try {
      HttpRequest request = HttpRequest.newBuilder()
          .uri(URI.create(baseUrl() + path))
          .timeout(requestTimeout)
          .header("Content-Type", "application/json")
          .POST(HttpRequest.BodyPublishers.ofString(body))
          .build();
      HttpResponse<String> response =
          http.send(request, HttpResponse.BodyHandlers.ofString());
      if (response.statusCode() != 200) {
        throw new InferenceException(
            "HTTP " + response.statusCode() + ": " + response.body());
      }
      return response.body();
    } catch (IOException | InterruptedException e) {
      throw new InferenceException("request failed: " + path, e);
    }
  }
}
