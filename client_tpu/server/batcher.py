"""Server-side dynamic batching with pipelined execution.

The TPU-first equivalent of Triton's dynamic batcher (the scheduler
the reference's perf docs benchmark against and which BASELINE.md's
"BERT dynamic batch" config presumes): concurrent single requests are
fused along the batch dimension into one XLA call — larger MXU
matmuls, one compile-shape per preferred size, far less per-request
dispatch overhead — then the stacked outputs are split back per
request.

Three mechanisms turn the naive gather->execute->fetch->split loop
into a pipeline:

* **Per-shape bucket queues.** Requests land in the queue keyed by
  their (per-sample shape, params) signature. A shape change no longer
  flushes the in-progress bucket — each shape accumulates toward its
  own preferred size on its own deadline, so interleaved traffic of
  two shapes fuses both instead of fragmenting each.

* **Adaptive queue delay** (opt-in via ``delay_min_us`` /
  ``delay_max_us``). For models that set the bounds, the batcher
  tracks the observed inter-arrival gap (EMA) and sizes the gather
  window to the time it actually takes to fill the largest preferred
  batch, clamped to ``[delay_min_us, delay_max_us]``. Sparse traffic
  collapses to the lower bound (no latency tax waiting for requests
  that are not coming); bursty traffic extends toward the upper bound
  so BERT-style concurrent singles fill a preferred 32/64 instead of
  dispatching at whatever arrived in the fixed window. Models that
  set neither bound keep Triton semantics: ``max_queue_delay_us`` is
  a hard ceiling.

* **Two-stage compute/fetch pipeline.** The gather thread dispatches
  fused batch N+1 to the device while batch N's stacked outputs are
  still fetching device->host on the fetch pool. In-flight depth is
  bounded (``pipeline_depth``), a failed batch poisons only its own
  requests, and stop() drains every queued request before the pools
  shut down. The :class:`_OverlapTracker` measures how much fetch
  wall-clock actually overlapped compute — the served-path number the
  statistics endpoints report as ``overlap_ratio``.

Sequence requests route through the sequence scheduler
(client_tpu.server.sequence) instead of entering here directly; under
the oldest strategy that scheduler dispatches per-sequence STEPS into
this batcher (controls and device-resident state already attached,
sequence_* params stripped), so steps from distinct sequences fuse
like any other concurrent requests."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from client_tpu.server import tracing as spantrace
from client_tpu.utils import InferenceServerException

NANOS_PER_US = 1_000


class _Pending:
    __slots__ = ("inputs", "params", "batch", "shape_key", "event",
                 "outputs", "error", "enqueue_ns", "queue_ns", "leader",
                 "deadline_ns", "trace", "done_ns", "queue_from_ns")

    def __init__(self, inputs, params, batch, shape_key,
                 timeout_ns: int = 0, trace=None):
        self.inputs = inputs
        self.params = params
        self.batch = batch
        self.shape_key = shape_key
        self.event = threading.Event()
        self.outputs = None
        self.error: Optional[Exception] = None
        self.enqueue_ns = time.monotonic_ns()
        self.queue_ns = 0
        # True for the request that represents the fused execution in
        # the server's execution_count statistic.
        self.leader = False
        # Absolute queue deadline (0 = none). Expired requests are
        # dropped BEFORE dispatch — a request nobody is waiting for
        # must not occupy a TPU slot.
        self.deadline_ns = self.enqueue_ns + timeout_ns if timeout_ns else 0
        # Sampled requests carry their RequestTrace; the execution
        # stage records queue/batch/fetch spans into it (shared spans
        # for the fused work). None = unsampled, zero cost.
        self.trace = trace
        # Completion stamp (_finish) so the request thread can span
        # its own wake latency; queue_from_ns backdates the queue span
        # to the caller's last boundary (covers scheduler creation and
        # enqueue locking, not just time spent in the bucket).
        self.done_ns = 0
        self.queue_from_ns = 0


class _OverlapTracker:
    """Wall-clock accounting for the compute/fetch pipeline: cumulative
    ns with >=1 fused execution in flight (compute), >=1 device->host
    output fetch in flight (fetch), and overlap — fetch time during
    which ANY other pipeline stage (another batch's compute dispatch or
    another fetch) was simultaneously in flight. Counting concurrent
    fetches matters because async-dispatch models return lazy device
    arrays: their device compute completes inside the fetch stage's
    host materialization, so on such models pipelining manifests as
    overlapping fetches rather than a long blocking compute span. The
    overlap/fetch ratio is the measure of how much of the fetch tax
    the pipeline hid behind other in-flight work (host-observed; for
    async models compute_ns is the dispatch span, a lower bound)."""

    __slots__ = ("_lock", "_compute", "_fetch", "_last_ns",
                 "compute_ns", "fetch_ns", "overlap_ns")

    def __init__(self):
        self._lock = threading.Lock()
        self._compute = 0
        self._fetch = 0
        self._last_ns = time.monotonic_ns()
        self.compute_ns = 0
        self.fetch_ns = 0
        self.overlap_ns = 0

    def _shift(self, d_compute: int, d_fetch: int) -> None:
        with self._lock:
            # Clock read INSIDE the lock: a stale `now` captured before
            # a contending thread advanced _last_ns would yield a
            # negative dt and corrupt the counters.
            now = time.monotonic_ns()
            dt = now - self._last_ns
            self._last_ns = now
            if self._compute > 0:
                self.compute_ns += dt
            if self._fetch > 0:
                self.fetch_ns += dt
            if self._fetch > 0 and self._compute + self._fetch >= 2:
                self.overlap_ns += dt
            self._compute += d_compute
            self._fetch += d_fetch

    def enter_compute(self):
        self._shift(1, 0)

    def exit_compute(self):
        self._shift(-1, 0)

    def enter_fetch(self):
        self._shift(0, 1)

    def exit_fetch(self):
        self._shift(0, -1)

    def snapshot(self) -> Tuple[int, int, int]:
        """(compute_ns, fetch_ns, overlap_ns), advanced to now."""
        self._shift(0, 0)
        with self._lock:
            return self.compute_ns, self.fetch_ns, self.overlap_ns


class DynamicBatcher:
    """One batcher (and gather thread) per served model.

    ``stats_hook(executed_batch_size, compute_ns, fetch_ns)`` is called
    once per successful fused execution — the server core feeds its
    per-model batch-size histogram from it."""

    def __init__(self, model, max_queue_delay_us: int = 500,
                 preferred_batch_sizes: Optional[List[int]] = None,
                 delay_min_us: int = 0, delay_max_us: int = 0,
                 pipeline_depth: int = 0, fetch_workers: int = 0,
                 stats_hook: Optional[Callable[[int, int, int],
                                               None]] = None,
                 max_queue_size: int = 0,
                 default_timeout_us: int = 0,
                 allow_timeout_override: bool = True,
                 timeout_action: str = "REJECT",
                 reject_hook: Optional[Callable[[], None]] = None,
                 timeout_hook: Optional[Callable[[], None]] = None):
        self._model = model
        # Queue policy (Triton ModelQueuePolicy semantics):
        # max_queue_size bounds total pending requests (0 = unbounded;
        # overflow is rejected UNAVAILABLE at admission, never
        # enqueued); default_timeout_us starts each request's queue
        # deadline, overridable per request by its `timeout` parameter
        # when allow_timeout_override is set. timeout_action REJECT
        # expires deadline-passed requests before dispatch; DELAY keeps
        # them queued (the deadline becomes advisory) — they execute
        # whenever their bucket dispatches.
        self._max_queue_size = max(int(max_queue_size), 0)
        self._default_timeout_ns = max(int(default_timeout_us), 0) \
            * NANOS_PER_US
        self._allow_timeout_override = bool(allow_timeout_override)
        self._timeout_reject = str(timeout_action).upper() != "DELAY"
        self._reject_hook = reject_hook
        self._timeout_hook = timeout_hook
        # Latches true at the first deadlined enqueue; until then the
        # expiry scan short-circuits, so models that never use
        # timeouts pay nothing on the hot gather path.
        self._any_deadlines = self._default_timeout_ns > 0
        self._max_batch = max(int(model.max_batch_size), 1)
        self._delay_ns = max_queue_delay_us * NANOS_PER_US
        self._preferred = sorted(
            s for s in (preferred_batch_sizes or []) if s <= self._max_batch
        )
        # Adaptive-delay bounds. Adaptation is OPT-IN: a model that
        # sets delay_min_us/delay_max_us accepts a gather window that
        # tracks the arrival rate inside those bounds; without them
        # max_queue_delay_us stays the hard ceiling it is in Triton —
        # silently stretching an existing config's "max" 16x would be
        # a latency regression nobody asked for.
        self._adaptive = delay_min_us > 0 or delay_max_us > 0
        self._delay_min_ns = (delay_min_us * NANOS_PER_US
                              if delay_min_us > 0 else self._delay_ns)
        self._delay_max_ns = (delay_max_us * NANOS_PER_US
                              if delay_max_us > 0
                              else max(self._delay_ns * 16, self._delay_ns))
        if not self._adaptive:
            self._delay_max_ns = self._delay_ns
        self._cur_delay_ns = min(max(self._delay_ns, self._delay_min_ns),
                                 self._delay_max_ns)
        # Inter-arrival EMA (ns); 0 until two requests have arrived.
        self._ia_ema_ns = 0.0
        self._last_arrival_ns = 0
        # Per-shape bucket queues, insertion-ordered so draining and
        # deadline scans visit older shapes first. _pending_total
        # mirrors the summed queue lengths so admission control and
        # the stats gauge read it in O(1) on the hot paths.
        self._buckets: "OrderedDict[tuple, List[_Pending]]" = OrderedDict()
        self._pending_total = 0
        self._cv = threading.Condition()
        self._stopping = False
        # Bounded pipeline: at most this many fused batches dispatched
        # but not yet finished (compute or fetch still pending).
        self._depth = pipeline_depth if pipeline_depth > 0 else 4
        self._inflight = 0
        self._tracker = _OverlapTracker()
        self._stats_hook = stats_hook
        from concurrent.futures import ThreadPoolExecutor

        # Host fetches of fused outputs run here so the exec workers
        # keep dispatching; concurrent device->host transfers pipeline.
        # Sized from the pipeline depth unless the model pins a count.
        self._fetch_workers = (fetch_workers if fetch_workers > 0
                               else max(2, self._depth))
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=self._fetch_workers,
            thread_name_prefix="batch-fetch")
        # Bucket executions run here, NOT on the gather thread: a
        # model whose infer() blocks (an ensemble fetching its final
        # outputs, any host-side model) would otherwise serialize the
        # whole batcher at one bucket per blocking round trip; in the
        # pool, consecutive buckets' device work and transfers
        # pipeline. Buckets are mutually independent, so cross-bucket
        # completion order is free.
        self._exec_pool = ThreadPoolExecutor(
            max_workers=max(2, self._depth),
            thread_name_prefix="batch-exec")
        self._thread = threading.Thread(target=self._gather_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        """Stops accepting work and drains: every queued request is
        still executed (deadlines are void once stopping), then the
        pools shut down after their in-flight batches finish."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        self._exec_pool.shutdown(wait=True)
        self._fetch_pool.shutdown(wait=True)

    # -- request side ----------------------------------------------------

    def infer(self, inputs: Dict[str, np.ndarray], params: dict,
              batch: int, trace=None,
              queue_from_ns: int = 0) -> Dict[str, np.ndarray]:
        """Blocks until this request's slice of a fused execution is
        ready. `batch` is the request's own batch-dim size; `trace` is
        the request's RequestTrace when sampled (never part of the
        fusion fingerprint — tracing must not fragment batches), and
        `queue_from_ns` backdates its queue span to the caller's last
        span boundary."""
        shape_key = (
            tuple(
                (name, array.shape[1:], array.dtype.str)
                for name, array in sorted(inputs.items())
            ),
            _params_fingerprint(params),
        )
        pending = _Pending(inputs, params, batch, shape_key,
                           timeout_ns=self._timeout_ns_for(params),
                           trace=trace)
        pending.queue_from_ns = queue_from_ns
        with self._cv:
            if self._stopping:
                raise InferenceServerException(
                    "server is shutting down", status="UNAVAILABLE")
            if self._max_queue_size > 0 \
                    and self._pending_total >= self._max_queue_size:
                # Admission control: overflow is rejected here, at the
                # door, so a saturated queue sheds load in O(1) instead
                # of growing without bound and timing everyone out.
                if self._reject_hook is not None:
                    try:
                        self._reject_hook()
                    except Exception:  # noqa: BLE001 — stats only
                        pass
                raise InferenceServerException(
                    "request for model '%s' rejected: exceeds "
                    "max_queue_size %d"
                    % (getattr(self._model, "name", "?"),
                       self._max_queue_size),
                    status="UNAVAILABLE")
            if pending.deadline_ns:
                self._any_deadlines = True
            now = pending.enqueue_ns
            if self._last_arrival_ns:
                gap = now - self._last_arrival_ns
                # Only intra-burst spacing feeds the EMA. A closed
                # loop's clients all block on the in-flight batch, so
                # each cycle shows one long idle gap; folding it in
                # would inflate the EMA (and with it the idle cutoff)
                # until the stall detector could never fire. The
                # threshold is FIXED (2x the configured delay) — tying
                # it to the adaptive window would feed back: a larger
                # window folds larger gaps, inflating the EMA, pinning
                # the window at delay_max.
                if gap <= 2 * max(self._delay_ns, self._delay_min_ns):
                    self._ia_ema_ns = (
                        gap if self._ia_ema_ns <= 0
                        else 0.875 * self._ia_ema_ns + 0.125 * gap)
            self._last_arrival_ns = now
            queue = self._buckets.get(shape_key)
            if queue is None:
                queue = self._buckets[shape_key] = []
            queue.append(pending)
            self._pending_total += 1
            self._cv.notify_all()
        pending.event.wait()
        if trace is not None and pending.done_ns:
            # Wake latency: the batch finished (done_ns stamped by
            # _finish) but this thread had to be rescheduled — real
            # queueing under load, spanned so the timeline tiles.
            trace.add_timed(spantrace.SPAN_QUEUE, pending.done_ns,
                            time.monotonic_ns(), {"phase": "wake"})
        if pending.error is not None:
            raise pending.error
        return pending.outputs, pending.queue_ns, pending.leader

    # -- queue policy -----------------------------------------------------

    def _timeout_ns_for(self, params: dict) -> int:
        """Effective queue timeout for one request: the per-request
        `timeout` parameter (microseconds, KServe-v2) when overrides
        are allowed, else the model's default_queue_policy_timeout_us;
        0 = no deadline."""
        timeout_ns = self._default_timeout_ns
        if self._allow_timeout_override:
            override = params.get("timeout")
            if override is not None:
                try:
                    timeout_ns = max(int(override), 0) * NANOS_PER_US
                except (TypeError, ValueError):
                    pass  # malformed timeouts fall back to the default
        return timeout_ns

    def _expire_locked(self, now: int) -> Optional[int]:
        """Drops deadline-passed requests (timeout_action REJECT) and
        returns the earliest live deadline for the gather wake-up, or
        None when nothing is deadlined. Caller holds the lock. Expiry
        runs BEFORE bucket selection so an expired request never
        reaches the device; deadlines are void while draining on stop
        (stop() promises execution)."""
        if self._stopping or not self._timeout_reject \
                or not self._any_deadlines:
            return None
        earliest: Optional[int] = None
        expired: List[_Pending] = []
        for shape_key in list(self._buckets):
            queue = self._buckets[shape_key]
            live = []
            for pending in queue:
                if pending.deadline_ns and now >= pending.deadline_ns:
                    pending.queue_ns = now - pending.enqueue_ns
                    expired.append(pending)
                    continue
                if pending.deadline_ns:
                    if earliest is None or pending.deadline_ns < earliest:
                        earliest = pending.deadline_ns
                live.append(pending)
            if len(live) != len(queue):
                if live:
                    queue[:] = live
                else:
                    del self._buckets[shape_key]
        self._pending_total -= len(expired)
        for pending in expired:
            pending.error = InferenceServerException(
                "request for model '%s' timed out in queue after "
                "%d us" % (getattr(self._model, "name", "?"),
                           pending.queue_ns // NANOS_PER_US),
                status="DEADLINE_EXCEEDED")
            pending.event.set()
            if self._timeout_hook is not None:
                try:
                    self._timeout_hook()
                except Exception:  # noqa: BLE001 — stats only
                    pass
        return earliest

    # -- adaptive delay ---------------------------------------------------

    def _adaptive_delay_ns(self) -> int:
        """Gather-window size for the current arrival rate (caller
        holds the lock). Sized so a full preferred batch has time to
        accumulate — but only for models that opted into adaptation
        (set delay bounds) AND declared preferred sizes, and only when
        arrivals are frequent enough that waiting can plausibly fill
        one. The idle-gap cutoff in _take_ready_bucket keeps the
        stretched window from taxing bounded closed-loop traffic."""
        ema = self._ia_ema_ns
        if not self._adaptive or not self._preferred \
                or self._preferred[-1] <= 1 or ema <= 0:
            delay = self._delay_ns
            return int(min(max(delay, self._delay_min_ns),
                           self._delay_max_ns))
        target = ema * (self._preferred[-1] - 1)
        target = min(max(target, self._delay_min_ns), self._delay_max_ns)
        # Taper toward the floor as traffic thins instead of cliffing:
        # `g` is how many arrivals the longest allowed window can
        # plausibly catch. At g<=2 waiting cannot form a batch (floor);
        # at g>=4 the full target applies; linear in between, so the
        # window doesn't oscillate when the rate hovers at a boundary.
        g = self._delay_max_ns / ema
        if g <= 2:
            delay = self._delay_min_ns
        elif g < 4:
            delay = self._delay_min_ns + \
                (target - self._delay_min_ns) * (g - 2) / 2
        else:
            delay = target
        return int(min(max(delay, self._delay_min_ns), self._delay_max_ns))

    def _idle_cutoff_ns(self, delay_ns: int) -> int:
        """How long the arrival stream may stall before a partial
        bucket dispatches early (caller holds the lock). Bounded-
        concurrency closed loops stop producing once every client is
        queued — detecting the stalled stream and dispatching beats
        burning the rest of a window sized for traffic that cannot
        arrive. Never below delay_min (the configured latency floor)."""
        ema = int(self._ia_ema_ns)
        if ema <= 0:
            return delay_ns
        return min(max(4 * ema, self._delay_min_ns), delay_ns)

    # -- gather thread ---------------------------------------------------

    def _gather_loop(self):
        while True:
            bucket: Optional[List[_Pending]] = None
            with self._cv:
                while bucket is None:
                    if self._stopping and not self._buckets:
                        return
                    if self._inflight >= self._depth:
                        # Pipeline full: woken by a batch completion —
                        # but queued deadlines must still expire, so
                        # sleep only until the earliest one.
                        wake = self._expire_locked(time.monotonic_ns())
                        if wake is None:
                            self._cv.wait()
                        else:
                            self._cv.wait(timeout=max(
                                wake - time.monotonic_ns(), 0) / 1e9)
                        continue
                    now = time.monotonic_ns()
                    bucket, wake_ns = self._take_ready_bucket(now)
                    if bucket is not None:
                        break
                    if not self._buckets:
                        self._cv.wait()
                    else:
                        self._cv.wait(
                            timeout=max(wake_ns - now, 0) / 1e9)
                self._inflight += 1
            try:
                self._exec_pool.submit(self._execute, bucket)
            except RuntimeError:  # pool shut down mid-stop
                self._execute(bucket)

    def _take_ready_bucket(self, now: int):
        """Pops and returns the ready bucket with the OLDEST head
        request (full to the largest preferred size / max batch, past
        its adaptive deadline, past the idle-gap cutoff, or draining
        on stop); otherwise (None, earliest_wake_ns). Oldest-head
        order keeps a flooded shape from starving a rare shape whose
        deadline expired while the flood's queue stayed permanently
        full. Caller holds the lock."""
        expire_wake = self._expire_locked(now)
        if not self._buckets:
            return None, expire_wake
        self._cur_delay_ns = delay = self._adaptive_delay_ns()
        full_at = self._preferred[-1] if self._preferred else self._max_batch
        # Arrival stream stalled (bounded closed loop fully queued):
        # partial buckets dispatch now instead of waiting out a window
        # sized for arrivals that cannot come.
        stalled = (self._last_arrival_ns > 0 and
                   now - self._last_arrival_ns >= self._idle_cutoff_ns(delay))
        ready_key = None
        ready_take = 0
        ready_head = None
        earliest: Optional[int] = None
        for shape_key, queue in self._buckets.items():
            take = 0
            total = 0
            for pending in queue:
                if total + pending.batch > self._max_batch:
                    break
                total += pending.batch
                take += 1
                if total >= full_at:
                    break
            if take == 0:
                # Head request alone exceeds max_batch capacity only
                # when batch > max_batch (validated upstream) — run it
                # alone rather than wedge the queue.
                take = 1
            head_ns = queue[0].enqueue_ns
            deadline = head_ns + delay
            if (total >= full_at or now >= deadline or stalled
                    or self._stopping):
                if ready_head is None or head_ns < ready_head:
                    ready_key, ready_take, ready_head = \
                        shape_key, take, head_ns
                continue
            wake = min(deadline,
                       self._last_arrival_ns + self._idle_cutoff_ns(delay))
            if earliest is None or wake < earliest:
                earliest = wake
        if expire_wake is not None and (earliest is None
                                        or expire_wake < earliest):
            # Queue-policy deadlines must wake the gather thread even
            # when every bucket's dispatch deadline lies further out.
            earliest = expire_wake
        if ready_key is not None:
            queue = self._buckets[ready_key]
            bucket = queue[:ready_take]
            del queue[:ready_take]
            self._pending_total -= ready_take
            if not queue:
                del self._buckets[ready_key]
            return bucket, None
        return None, earliest

    def _padded_size(self, total: int) -> int:
        """Rounds the fused batch up to a stable compile shape: the
        smallest preferred size that fits, else the next power of two
        (capped at max_batch). XLA traces once per shape — unpadded
        fusing would recompile for every distinct request mix."""
        for size in self._preferred:
            if total <= size:
                return size
        if total >= self._max_batch:
            return self._max_batch
        size = 1
        while size < total:
            size <<= 1
        return min(size, self._max_batch)

    # -- execution stage (exec pool) --------------------------------------

    def _execute(self, bucket: List[_Pending]):
        start_ns = time.monotonic_ns()
        bucket[0].leader = True
        traced = [p.trace for p in bucket if p.trace is not None]
        for pending in bucket:
            pending.queue_ns = start_ns - pending.enqueue_ns
            if pending.trace is not None:
                pending.trace.add_timed(
                    spantrace.SPAN_QUEUE,
                    pending.queue_from_ns or pending.enqueue_ns, start_ns)
        try:
            total = sum(p.batch for p in bucket)
            target = self._padded_size(total)
            passthrough = len(bucket) == 1 and bucket[0].batch == target
            self._tracker.enter_compute()
            try:
                if passthrough:
                    outputs = self._model.infer(
                        bucket[0].inputs, bucket[0].params)
                else:
                    fused = {
                        name: _fuse_chunks(
                            [p.inputs[name] for p in bucket], target, total)
                        for name in bucket[0].inputs
                    }
                    outputs = self._model.infer(fused, bucket[0].params)
            finally:
                self._tracker.exit_compute()
            compute_end_ns = time.monotonic_ns()
            compute_ns = compute_end_ns - start_ns
            if traced:
                # ONE batch-execution span shared by every sampled
                # member: same span id in each trace, carrying the
                # fused batch size and compile bucket — the reader
                # both attributes the time per request and sees the
                # work was done once. Its end bound is reused as the
                # fetch chain's start so no slice between the stages
                # goes untracked.
                batch_span = spantrace.shared_span(
                    spantrace.SPAN_BATCH_EXECUTE, start_ns,
                    compute_end_ns,
                    {"batch": total, "padded_batch": target,
                     "requests": len(bucket)})
                for trace in traced:
                    trace.add(batch_span)
            if passthrough:
                bucket[0].outputs = outputs
                self._finish(bucket, target, compute_ns, 0,
                             done_from=compute_end_ns)
                return
            if all(
                isinstance(p.inputs[name], np.ndarray)
                for p in bucket for name in p.inputs
            ):
                # Every request arrived over the wire and will be
                # serialized to host bytes anyway: fetch the fused
                # output ONCE (one relay round-trip for the whole
                # bucket, not n slice transfers) — and do it on the
                # fetch pool so this exec worker (and the gather
                # thread) can dispatch the NEXT bucket while this
                # transfer is in flight.
                for array in outputs.values():
                    if hasattr(array, "copy_to_host_async"):
                        array.copy_to_host_async()
                try:
                    self._fetch_pool.submit(
                        self._finish_host_bucket, bucket, outputs,
                        target, compute_ns)
                except RuntimeError:  # pool shut down mid-stop
                    self._finish_host_bucket(bucket, outputs, target,
                                             compute_ns)
            else:
                # Device-resident bucket (TPU-shm path): slices are
                # lazy device views; outputs stay in HBM end-to-end.
                self._scatter(bucket, outputs)
                self._finish(bucket, target, compute_ns, 0,
                             done_from=compute_end_ns)
        except Exception as e:
            self._assign_error(bucket, e)
            self._finish(bucket, 0, 0, 0, ok=False)

    # -- fetch stage (fetch pool) -----------------------------------------

    def _finish_host_bucket(self, bucket: List[_Pending], outputs,
                            target: int, compute_ns: int) -> None:
        fetch_start = time.monotonic_ns()
        self._tracker.enter_fetch()
        traced = [p.trace for p in bucket if p.trace is not None]
        mark_ns = 0
        try:
            if traced:
                # Per-output relay fetch, individually timed: one
                # shared span per output tensor (the whole bucket
                # rides one transfer) — the measured form of ROADMAP
                # item 1's relay_fetch_ms_est. Boundaries chain (each
                # span starts where the previous ended, the first at
                # the pool handoff) so the fetch stage tiles.
                host = {}
                mark_ns = fetch_start
                for name, array in outputs.items():
                    host[name] = np.asarray(array)
                    end_ns = time.monotonic_ns()
                    fetch_span = spantrace.shared_span(
                        spantrace.SPAN_RELAY_FETCH, mark_ns, end_ns,
                        {"output": name,
                         "nbytes": int(host[name].nbytes)})
                    mark_ns = end_ns
                    for trace in traced:
                        trace.add(fetch_span)
            else:
                host = {name: np.asarray(a) for name, a in outputs.items()}
            self._scatter(bucket, host)
        except Exception as e:  # noqa: BLE001 — waiters must wake
            self._assign_error(bucket, e)
            self._tracker.exit_fetch()
            self._finish(bucket, 0, 0, 0, ok=False)
            return
        self._tracker.exit_fetch()
        self._finish(bucket, target, compute_ns,
                     time.monotonic_ns() - fetch_start,
                     done_from=mark_ns)

    def _finish(self, bucket: List[_Pending], executed: int,
                compute_ns: int, fetch_ns: int, ok: bool = True,
                done_from: int = 0) -> None:
        """Completion for one fused batch: wake the waiters, record the
        execution, release the pipeline slot. ``done_from`` chains the
        wake-span base off the last compute/fetch boundary so the
        scatter/notify slice is attributed too."""
        done_ns = done_from or time.monotonic_ns()
        for pending in bucket:
            pending.done_ns = done_ns
            pending.event.set()
        if ok and self._stats_hook is not None:
            try:
                self._stats_hook(executed, compute_ns, fetch_ns)
            except Exception:  # noqa: BLE001 — stats never fail serving
                pass
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    @staticmethod
    def _scatter(bucket: List[_Pending], outputs) -> None:
        offset = 0
        for pending in bucket:
            pending.outputs = {
                name: array[offset:offset + pending.batch]
                for name, array in outputs.items()
            }
            offset += pending.batch

    @staticmethod
    def _assign_error(bucket: List[_Pending], e: Exception) -> None:
        error = e if isinstance(e, InferenceServerException) else \
            InferenceServerException(
                "batched inference failed: %s" % e, status="INTERNAL")
        for pending in bucket:
            pending.error = error

    # -- observability ----------------------------------------------------

    def stats_snapshot(self) -> dict:
        """Point-in-time pipeline gauges plus cumulative compute/fetch
        overlap counters (the statistics endpoints' pipeline_stats)."""
        with self._cv:
            pending = self._pending_total
            inflight = self._inflight
            delay_us = self._cur_delay_ns // NANOS_PER_US
        compute_ns, fetch_ns, overlap_ns = self._tracker.snapshot()
        return {
            "pending_count": pending,
            "inflight_count": inflight,
            "queue_delay_us": delay_us,
            "compute_ns": compute_ns,
            "fetch_ns": fetch_ns,
            "overlap_ns": overlap_ns,
            "overlap_ratio": (overlap_ns / fetch_ns) if fetch_ns else 0.0,
        }


def _fuse_chunks(chunks, target: int, total: int):
    """Assembles per-request input chunks into one batch of `target`
    rows (unfilled pad rows stay zero; they are computed and
    discarded).

    When any chunk is a device array (the TPU-shm path resolves
    inputs to ``jax.Array``s), fusion runs as device ops — a numpy
    concat here would silently drag every chunk back to host, defeating
    the arena's zero-copy design (the round-2 12-infer/s regression).
    The device path writes chunks into a zero buffer with
    ``dynamic_update_slice`` — start offsets are runtime values, so XLA
    compiles ONE kernel per (buffer, chunk) shape pair instead of one
    ``concatenate`` per distinct chunk-count/pad mix (the round-3
    steady-state recompile source)."""
    all_host = all(isinstance(c, np.ndarray) for c in chunks)
    if all_host:
        if target > total:
            pad_shape = (target - total,) + tuple(chunks[-1].shape[1:])
            if chunks[-1].dtype.kind == "O":  # BYTES: pad rows need
                pad = np.broadcast_to(  # valid payloads, not int 0
                    chunks[-1][-1:], pad_shape)
            else:
                pad = np.zeros(pad_shape, dtype=chunks[-1].dtype)
            chunks = chunks + [pad]
        return np.concatenate(chunks, axis=0)
    import jax
    import jax.numpy as jnp

    first = chunks[0]
    buf = jnp.zeros((target,) + tuple(first.shape[1:]), dtype=first.dtype)
    # np.int32 offsets are runtime arguments to the cached executable,
    # never baked-in constants — one compile per shape pair, period.
    zeros = (np.int32(0),) * (buf.ndim - 1)
    offset = 0
    for chunk in chunks:
        buf = jax.lax.dynamic_update_slice(
            buf, chunk, (np.int32(offset),) + zeros)
        offset += int(chunk.shape[0])
    return buf


def _params_fingerprint(params: dict):
    """Normalized, hashable view of request parameters. Requests are
    only fused when their parameters match — fusing would otherwise
    execute the whole bucket with the leader's params, silently
    dropping the rest (priority, custom params). `timeout` is excluded:
    the batcher enforces each request's deadline individually, so
    differing timeouts must not fragment fusion."""
    if not params:
        return ()
    return tuple(
        (key, repr(params[key])) for key in sorted(params)
        if key != "timeout"
    )


def wants_dynamic_batching(model) -> bool:
    return (
        getattr(model, "dynamic_batching", False)
        and int(getattr(model, "max_batch_size", 0)) > 1
        and not getattr(model, "decoupled", False)
    )
