"""Server-side dynamic batching with pipelined execution.

The TPU-first equivalent of Triton's dynamic batcher (the scheduler
the reference's perf docs benchmark against and which BASELINE.md's
"BERT dynamic batch" config presumes): concurrent single requests are
fused along the batch dimension into one XLA call — larger MXU
matmuls, one compile-shape per preferred size, far less per-request
dispatch overhead — then the stacked outputs are split back per
request.

Three mechanisms turn the naive gather->execute->fetch->split loop
into a pipeline:

* **Per-shape bucket queues.** Requests land in the queue keyed by
  their (per-sample shape, params) signature. A shape change no longer
  flushes the in-progress bucket — each shape accumulates toward its
  own preferred size on its own deadline, so interleaved traffic of
  two shapes fuses both instead of fragmenting each.

* **Adaptive queue delay** (opt-in via ``delay_min_us`` /
  ``delay_max_us``). For models that set the bounds, the batcher
  tracks the observed inter-arrival gap (EMA) and sizes the gather
  window to the time it actually takes to fill the largest preferred
  batch, clamped to ``[delay_min_us, delay_max_us]``. Sparse traffic
  collapses to the lower bound (no latency tax waiting for requests
  that are not coming); bursty traffic extends toward the upper bound
  so BERT-style concurrent singles fill a preferred 32/64 instead of
  dispatching at whatever arrived in the fixed window. Models that
  set neither bound keep Triton semantics: ``max_queue_delay_us`` is
  a hard ceiling.

* **Two-stage compute/fetch pipeline.** The gather thread dispatches
  fused batch N+1 to the device while batch N's stacked outputs are
  still fetching device->host on the fetch pool. In-flight depth is
  bounded (``pipeline_depth``), a failed batch poisons only its own
  requests, and stop() drains every queued request before the pools
  shut down. The :class:`_OverlapTracker` measures how much fetch
  wall-clock actually overlapped compute — the served-path number the
  statistics endpoints report as ``overlap_ratio``.

With ``priority_levels`` configured (Triton semantics: classes
``1..priority_levels``, 1 highest), each shape bucket segments its
queue per class and dispatch drains classes strictly in priority
order — a priority-1 request overtakes a bulk backlog at dispatch
time — with an aged-oldest slot every ``AGE_EVERY`` dispatches so
strict ordering cannot starve bulk. Priority is dispatch ORDER, not
fusion identity: mixed classes still fuse into one padded execution.
Overload degrades lowest-priority-first (the graceful-shedding
tentpole): past ``shed_watermark`` lowest-class arrivals are shed
with Retry-After, and at a hard-full queue a higher-priority arrival
displaces the newest lowest-class waiter instead of being rejected.

Sequence requests route through the sequence scheduler
(client_tpu.server.sequence) instead of entering here directly; under
the oldest strategy that scheduler dispatches per-sequence STEPS into
this batcher (controls and device-resident state already attached,
sequence_* params stripped), so steps from distinct sequences fuse
like any other concurrent requests."""

from __future__ import annotations

import contextlib
import inspect
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from client_tpu.server import tracing as spantrace
from client_tpu import status_map
from client_tpu.server import cancel as cancel_mod
from client_tpu.server.fetch import OutputFetcher
from client_tpu.server.qos import coerce_int, coerce_priority
from client_tpu.utils import InferenceServerException

NANOS_PER_US = 1_000


class _Pending:
    __slots__ = ("inputs", "params", "batch", "shape_key", "event",
                 "outputs", "error", "enqueue_ns", "queue_ns", "leader",
                 "deadline_ns", "trace", "done_ns", "queue_from_ns",
                 "priority", "wanted", "device_outputs")

    def __init__(self, inputs, params, batch, shape_key,
                 timeout_ns: int = 0, trace=None, priority: int = 0,
                 wanted=None, device_outputs=None):
        self.inputs = inputs
        self.params = params
        self.batch = batch
        self.shape_key = shape_key
        self.event = threading.Event()
        self.outputs = None
        self.error: Optional[Exception] = None
        self.enqueue_ns = time.monotonic_ns()
        self.queue_ns = 0
        # True for the request that represents the fused execution in
        # the server's execution_count statistic.
        self.leader = False
        # Absolute queue deadline (0 = none). Expired requests are
        # dropped BEFORE dispatch — a request nobody is waiting for
        # must not occupy a TPU slot.
        self.deadline_ns = self.enqueue_ns + timeout_ns if timeout_ns else 0
        # Sampled requests carry their RequestTrace; the execution
        # stage records queue/batch/fetch spans into it (shared spans
        # for the fused work). None = unsampled, zero cost.
        self.trace = trace
        # Completion stamp (_finish) so the request thread can span
        # its own wake latency; queue_from_ns backdates the queue span
        # to the caller's last boundary (covers scheduler creation and
        # enqueue locking, not just time spent in the bucket).
        self.done_ns = 0
        self.queue_from_ns = 0
        # QoS class (1..priority_levels, 1 highest; 0 = model has no
        # priority levels). Dispatch order, never fusion identity —
        # mixed-priority requests still fuse into one execution.
        self.priority = priority
        # The output names THIS member's request asked for (None =
        # everything the model produces). The overlapped fetch path
        # wakes a member as soon as its wanted outputs land — it never
        # waits out transfers of outputs it will not encode.
        self.wanted = wanted
        # True = the caller consumes device arrays directly (ensemble
        # dataflow interior stage): wake it with device SLICES at
        # compute end, never route it through the host fetch path.
        # None = infer from the member's input types (a wire request
        # decoded to numpy wants host outputs; the TPU-shm path's
        # device inputs keep outputs resident) — the pre-dataflow
        # behavior.
        self.device_outputs = device_outputs


class _Bucket:
    """One shape bucket's pending requests, segmented per priority
    class. Level 0 (priority disabled) degenerates to a single FIFO —
    the pre-QoS behavior, at the cost of one extra dict hop. Dispatch
    drains classes in ascending level order (1 = highest first), FIFO
    within a class; the caller holds the batcher lock throughout."""

    __slots__ = ("queues", "dispatches")

    def __init__(self):
        # level -> FIFO of _Pending, keys kept in ascending (highest
        # priority first) order so dispatch iteration is just
        # insertion order. Pending totals are the batcher's job
        # (_pending_total / _pending_by_priority) — no per-bucket
        # count is kept here.
        self.queues: "OrderedDict[int, List[_Pending]]" = OrderedDict()
        # This bucket's own dispatch count, driving the aged-oldest
        # slot: a batcher-global counter could beat periodically
        # against the bucket-selection pattern (e.g. two buckets
        # alternating with AGE_EVERY=4 always lands the aged slot on
        # the same bucket), letting bulk starve in the other.
        self.dispatches = 0

    def append(self, pending: _Pending) -> None:
        queue = self.queues.get(pending.priority)
        if queue is None:
            self.queues[pending.priority] = [pending]
            if len(self.queues) > 1:
                self.queues = OrderedDict(sorted(self.queues.items()))
        else:
            queue.append(pending)

    def head_ns(self) -> int:
        """Enqueue stamp of the OLDEST pending request across classes
        (each class queue is FIFO, so its head is its oldest)."""
        return min(queue[0].enqueue_ns for queue in self.queues.values())

    def plan(self, max_batch: int, full_at: int) -> int:
        """Dry-run of take(): the fused batch total a dispatch now
        would reach, visiting classes in priority order."""
        total = 0
        for queue in self.queues.values():
            for pending in queue:
                if total and (total + pending.batch > max_batch
                              or total >= full_at):
                    return total
                total += pending.batch
                if total >= full_at:
                    return total
        return total

    def take(self, max_batch: int, full_at: int,
             age_oldest: bool = False) -> List[_Pending]:
        """Pops the requests of one fused dispatch: strict priority
        order (class 1 drains first), except that with ``age_oldest``
        the globally-oldest request is seated FIRST regardless of its
        class — the weighted share of strict-then-weighted dispatch
        that keeps a saturating high-priority stream from starving
        bulk forever. The first request is always taken even when its
        batch alone exceeds max_batch (validated upstream; running it
        alone beats wedging the queue)."""
        taken: List[_Pending] = []
        total = 0
        if age_oldest and len(self.queues) > 1:
            oldest_level = min(
                self.queues,
                key=lambda level: self.queues[level][0].enqueue_ns)
            head = self.queues[oldest_level].pop(0)
            if not self.queues[oldest_level]:
                del self.queues[oldest_level]
            taken.append(head)
            total = head.batch
        done = False
        for level in list(self.queues):
            queue = self.queues[level]
            while queue:
                pending = queue[0]
                if taken and (total + pending.batch > max_batch
                              or total >= full_at):
                    # Stop the WHOLE take at the first non-fitting
                    # head: skipping it to seat a smaller lower-class
                    # request would invert priority order.
                    done = True
                    break
                taken.append(queue.pop(0))
                total += pending.batch
            if not queue:
                del self.queues[level]
            if done:
                break
        return taken

    def remove(self, pending: _Pending) -> bool:
        """Drops one specific pending (shed path). False if absent."""
        queue = self.queues.get(pending.priority)
        if not queue:
            return False
        try:
            queue.remove(pending)
        except ValueError:
            return False
        if not queue:
            del self.queues[pending.priority]
        return True


class _OverlapTracker:
    """Wall-clock accounting for the compute/fetch pipeline: cumulative
    ns with >=1 fused execution in flight (compute), >=1 device->host
    output fetch in flight (fetch), and overlap — fetch time during
    which ANY other pipeline stage (another batch's compute dispatch or
    another fetch) was simultaneously in flight. Counting concurrent
    fetches matters because async-dispatch models return lazy device
    arrays: their device compute completes inside the fetch stage's
    host materialization, so on such models pipelining manifests as
    overlapping fetches rather than a long blocking compute span. The
    overlap/fetch ratio is the measure of how much of the fetch tax
    the pipeline hid behind other in-flight work (host-observed; for
    async models compute_ns is the dispatch span, a lower bound)."""

    __slots__ = ("_lock", "_compute", "_fetch", "_last_ns",
                 "compute_ns", "fetch_ns", "overlap_ns")

    def __init__(self):
        self._lock = threading.Lock()
        self._compute = 0
        self._fetch = 0
        self._last_ns = time.monotonic_ns()
        self.compute_ns = 0
        self.fetch_ns = 0
        self.overlap_ns = 0

    def _shift(self, d_compute: int, d_fetch: int) -> None:
        with self._lock:
            # Clock read INSIDE the lock: a stale `now` captured before
            # a contending thread advanced _last_ns would yield a
            # negative dt and corrupt the counters.
            now = time.monotonic_ns()
            dt = now - self._last_ns
            self._last_ns = now
            if self._compute > 0:
                self.compute_ns += dt
            if self._fetch > 0:
                self.fetch_ns += dt
            if self._fetch > 0 and self._compute + self._fetch >= 2:
                self.overlap_ns += dt
            self._compute += d_compute
            self._fetch += d_fetch

    def enter_compute(self):
        self._shift(1, 0)

    def exit_compute(self):
        self._shift(-1, 0)

    def enter_fetch(self):
        self._shift(0, 1)

    def exit_fetch(self):
        self._shift(0, -1)

    def snapshot(self) -> Tuple[int, int, int]:
        """(compute_ns, fetch_ns, overlap_ns), advanced to now."""
        self._shift(0, 0)
        with self._lock:
            return self.compute_ns, self.fetch_ns, self.overlap_ns


class DynamicBatcher:
    """One batcher (and gather thread) per served model.

    ``stats_hook(executed_batch_size, compute_ns, fetch_ns)`` is called
    once per successful fused execution — the server core feeds its
    per-model batch-size histogram from it."""

    # Every Nth dispatch from a mixed-priority bucket seats the
    # globally-oldest request first (the "weighted" arm of
    # strict-then-weighted dispatch): lower classes keep a bounded
    # share of dispatch slots even under sustained priority-1 load.
    AGE_EVERY = 4

    def __init__(self, model, max_queue_delay_us: int = 500,
                 preferred_batch_sizes: Optional[List[int]] = None,
                 delay_min_us: int = 0, delay_max_us: int = 0,
                 pipeline_depth: int = 0, fetch_workers: int = 0,
                 stats_hook: Optional[Callable[[int, int, int],
                                               None]] = None,
                 max_queue_size: int = 0,
                 default_timeout_us: int = 0,
                 allow_timeout_override: bool = True,
                 timeout_action: str = "REJECT",
                 reject_hook: Optional[Callable[..., None]] = None,
                 timeout_hook: Optional[Callable[..., None]] = None,
                 priority_levels: int = 0,
                 default_priority_level: int = 0,
                 priority_policies: Optional[Dict[int, dict]] = None,
                 shed_watermark: float = 0.0,
                 shed_hook: Optional[Callable[..., None]] = None,
                 wasted_hook: Optional[Callable[[int], None]] = None,
                 execution_target=None,
                 telemetry=None,
                 overlapped_fetch: bool = True,
                 fetch_chunk_bytes: int = 0,
                 compile_scope: Optional[Callable] = None):
        self._model = model
        # Compile-attribution scope (client_tpu.server.devstats):
        # wraps each fused execution so XLA compiles triggered by a
        # fresh pow2 shape bucket attribute to this model + bucket.
        # The core passes None for replicated models — the replica's
        # own device queue owns attribution there.
        self._compile_scope = compile_scope
        # Always-on latency histograms (client_tpu.server.telemetry's
        # ServerTelemetry, or None): each fused execution records a
        # batch_execute observation and each host materialization a
        # relay_fetch observation — per execution, never per member
        # request, so the histogram counts work units. When a sampled
        # request rode the batch, its trace id lands on the bucket as
        # an exemplar (the hot-bucket -> span-tree join).
        self._telemetry = telemetry
        # The hand-off point to execution. By default fused batches run
        # on the model itself; an instance-group model passes its
        # ReplicaSet proxy here so every fused batch is health-routed
        # to one of N per-device replicas (client_tpu.server.replicas)
        # instead of a single fault domain. Config knobs above always
        # read from `model` — routing changes where a batch executes,
        # never how it was gathered.
        self._target = execution_target if execution_target is not None \
            else model
        # Priority scheduling (Triton priority_levels semantics):
        # classes 1..priority_levels, 1 highest; requests pick their
        # class via the `priority` parameter (coerced + validated by
        # qos.coerce_priority — out-of-range is INVALID_ARGUMENT, not
        # a silent drop). priority_policies maps a level to optional
        # {"max_queue_size", "default_timeout_us"} overrides.
        # shed_watermark (fraction of max_queue_size) arms graceful
        # load shedding: past it, lowest-class arrivals are shed with
        # Retry-After, and at a hard-full queue a higher-priority
        # arrival displaces the newest lowest-class waiter instead of
        # being turned away.
        self._priority_levels = max(int(priority_levels), 0)
        self._default_priority = int(default_priority_level)
        self._priority_policies = dict(priority_policies or {})
        self._shed_watermark = min(max(float(shed_watermark), 0.0), 1.0)
        self._shed_hook = shed_hook
        # Wasted-compute accounting (tpu_wasted_compute_us): called
        # with the device-ns share attributable to fused members that
        # were already cancelled when their batch completed — work
        # nobody read, priced by _finish.
        self._wasted_hook = wasted_hook
        # Controller-ordered shed (qos.ShedDirective, set by the
        # autoscale loop when the SLO is unmeetable at max scale):
        # while active, lowest-class arrivals shed at the door with
        # the directive's predicted-recovery Retry-After — depth-
        # independent, unlike the watermark gate below it.
        self._shed_directive = None
        self._pending_by_priority: Dict[int, int] = {}
        # Queue policy (Triton ModelQueuePolicy semantics):
        # max_queue_size bounds total pending requests (0 = unbounded;
        # overflow is rejected UNAVAILABLE at admission, never
        # enqueued); default_timeout_us starts each request's queue
        # deadline, overridable per request by its `timeout` parameter
        # when allow_timeout_override is set. timeout_action REJECT
        # expires deadline-passed requests before dispatch; DELAY keeps
        # them queued (the deadline becomes advisory) — they execute
        # whenever their bucket dispatches.
        self._max_queue_size = max(int(max_queue_size), 0)
        self._default_timeout_ns = max(int(default_timeout_us), 0) \
            * NANOS_PER_US
        self._allow_timeout_override = bool(allow_timeout_override)
        self._timeout_reject = str(timeout_action).upper() != "DELAY"
        self._reject_hook = reject_hook
        self._timeout_hook = timeout_hook
        # Latches true at the first deadlined enqueue; until then the
        # expiry scan short-circuits, so models that never use
        # timeouts pay nothing on the hot gather path.
        self._any_deadlines = self._default_timeout_ns > 0
        self._max_batch = max(int(model.max_batch_size), 1)
        self._delay_ns = max_queue_delay_us * NANOS_PER_US
        self._preferred = sorted(
            s for s in (preferred_batch_sizes or []) if s <= self._max_batch
        )
        # Adaptive-delay bounds. Adaptation is OPT-IN: a model that
        # sets delay_min_us/delay_max_us accepts a gather window that
        # tracks the arrival rate inside those bounds; without them
        # max_queue_delay_us stays the hard ceiling it is in Triton —
        # silently stretching an existing config's "max" 16x would be
        # a latency regression nobody asked for.
        self._adaptive = delay_min_us > 0 or delay_max_us > 0
        self._delay_min_ns = (delay_min_us * NANOS_PER_US
                              if delay_min_us > 0 else self._delay_ns)
        self._delay_max_ns = (delay_max_us * NANOS_PER_US
                              if delay_max_us > 0
                              else max(self._delay_ns * 16, self._delay_ns))
        if not self._adaptive:
            self._delay_max_ns = self._delay_ns
        self._cur_delay_ns = min(max(self._delay_ns, self._delay_min_ns),
                                 self._delay_max_ns)
        # Inter-arrival EMA (ns); 0 until two requests have arrived.
        self._ia_ema_ns = 0.0
        self._last_arrival_ns = 0
        # Per-shape bucket queues (each segmented per priority class),
        # insertion-ordered so draining and deadline scans visit older
        # shapes first. _pending_total mirrors the summed queue
        # lengths so admission control and the stats gauge read it in
        # O(1) on the hot paths.
        self._buckets: "OrderedDict[tuple, _Bucket]" = OrderedDict()
        self._pending_total = 0
        self._cv = threading.Condition()
        self._stopping = False
        # Bounded pipeline: at most this many fused batches dispatched
        # but not yet finished (compute or fetch still pending).
        self._depth = pipeline_depth if pipeline_depth > 0 else 4
        self._inflight = 0
        self._tracker = _OverlapTracker()
        self._stats_hook = stats_hook
        from concurrent.futures import ThreadPoolExecutor

        # Host fetches of fused outputs run here so the exec workers
        # keep dispatching; concurrent device->host transfers pipeline.
        # Sized from the pipeline depth unless the model pins a count.
        self._fetch_workers = (fetch_workers if fetch_workers > 0
                               else max(2, self._depth))
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=self._fetch_workers,
            thread_name_prefix="batch-fetch")
        # Overlapped output-fetch subsystem (client_tpu.server.fetch):
        # its OWN pool lands per-output/per-chunk transfers while
        # _fetch_pool keeps orchestrating whole-bucket completions.
        # Separate pools by design: an orchestration job WAITS on
        # landing jobs, so sharing one bounded pool could deadlock
        # with every worker parked in an orchestrator. None = the
        # model opted out (overlapped_fetch=False) — the legacy serial
        # np.asarray fetch, kept as the bench A/B baseline arm.
        self._fetcher = (OutputFetcher(workers=self._fetch_workers,
                                       chunk_bytes=fetch_chunk_bytes)
                         if overlapped_fetch else None)
        # Bucket executions run here, NOT on the gather thread: a
        # model whose infer() blocks (an ensemble fetching its final
        # outputs, any host-side model) would otherwise serialize the
        # whole batcher at one bucket per blocking round trip; in the
        # pool, consecutive buckets' device work and transfers
        # pipeline. Buckets are mutually independent, so cross-bucket
        # completion order is free.
        self._exec_pool = ThreadPoolExecutor(
            max_workers=max(2, self._depth),
            thread_name_prefix="batch-exec")
        self._thread = threading.Thread(target=self._gather_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        """Stops accepting work and drains: every queued request is
        still executed (deadlines are void once stopping), then the
        pools shut down after their in-flight batches finish."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        self._exec_pool.shutdown(wait=True)
        self._fetch_pool.shutdown(wait=True)
        if self._fetcher is not None:
            # After the orchestration pool: its draining completions
            # still wait on landing jobs running here.
            self._fetcher.shutdown()

    # -- request side ----------------------------------------------------

    def infer(self, inputs: Dict[str, np.ndarray], params: dict,
              batch: int, trace=None,
              queue_from_ns: int = 0,
              priority: Optional[int] = None,
              wanted_outputs=None,
              device_outputs=None,
              cancel=None) -> Dict[str, np.ndarray]:
        """Blocks until this request's slice of a fused execution is
        ready. `batch` is the request's own batch-dim size; `trace` is
        the request's RequestTrace when sampled (never part of the
        fusion fingerprint — tracing must not fragment batches), and
        `queue_from_ns` backdates its queue span to the caller's last
        span boundary. `priority` is the caller's already-coerced
        class when it validated the parameter itself (the core does,
        for stats labeling — one coercion, one source of truth);
        None = coerce from params here. `wanted_outputs` is the set of
        output names the request asked for (None = all): the
        overlapped fetch wakes this call as soon as those land, even
        while the fused batch's other outputs are still in flight.
        `device_outputs=True` marks a device-resident consumer
        (ensemble dataflow interior stage): it wakes with device
        slices at compute end and never rides the host fetch — while
        still fusing into the same shape bucket as wire traffic."""
        shape_key = (
            tuple(
                (name, array.shape[1:], array.dtype.str)
                for name, array in sorted(inputs.items())
            ),
            _params_fingerprint(params),
        )
        if priority is None:
            priority = self._priority_for(params)  # INVALID_ARGUMENT
        pending = _Pending(inputs, params, batch, shape_key,
                           timeout_ns=self._timeout_ns_for(params,
                                                           priority),
                           trace=trace, priority=priority,
                           wanted=(frozenset(wanted_outputs)
                                   if wanted_outputs else None),
                           device_outputs=device_outputs)
        pending.queue_from_ns = queue_from_ns
        with self._cv:
            if self._stopping:
                # Retry-After here is for the fleet case: a draining
                # replica's clients should re-resolve/failover, not
                # hammer the dying process.
                raise status_map.retryable_error(
                    "server is shutting down", retry_after_s=1.0)
            self._admit_locked(pending)
            if pending.deadline_ns:
                self._any_deadlines = True
            now = pending.enqueue_ns
            if self._last_arrival_ns:
                gap = now - self._last_arrival_ns
                # Only intra-burst spacing feeds the EMA. A closed
                # loop's clients all block on the in-flight batch, so
                # each cycle shows one long idle gap; folding it in
                # would inflate the EMA (and with it the idle cutoff)
                # until the stall detector could never fire. The
                # threshold is FIXED (2x the configured delay) — tying
                # it to the adaptive window would feed back: a larger
                # window folds larger gaps, inflating the EMA, pinning
                # the window at delay_max.
                if gap <= 2 * max(self._delay_ns, self._delay_min_ns):
                    self._ia_ema_ns = (
                        gap if self._ia_ema_ns <= 0
                        else 0.875 * self._ia_ema_ns + 0.125 * gap)
            self._last_arrival_ns = now
            bucket = self._buckets.get(shape_key)
            if bucket is None:
                bucket = self._buckets[shape_key] = _Bucket()
            bucket.append(pending)
            self._pending_total += 1
            if self._priority_levels:
                self._pending_by_priority[priority] = \
                    self._pending_by_priority.get(priority, 0) + 1
            self._cv.notify_all()
        if cancel is not None:
            # Event-driven wakeup, not a poll: the token fires
            # _cancel_pending which drops a still-queued member (or
            # marks a dispatched one stage="execute" — its fused XLA
            # call is never unpadded, its slice simply isn't fetched)
            # and sets the event. Removal is paired in a finally so a
            # recycled token can never poke a completed pending.
            handle = cancel.on_cancel(
                lambda: self._cancel_pending(pending))
            try:
                pending.event.wait()
            finally:
                cancel.remove_callback(handle)
        else:
            pending.event.wait()
        if trace is not None and pending.done_ns:
            # Wake latency: the batch finished (done_ns stamped by
            # _finish) but this thread had to be rescheduled — real
            # queueing under load, spanned so the timeline tiles.
            trace.add_timed(spantrace.SPAN_QUEUE, pending.done_ns,
                            time.monotonic_ns(), {"phase": "wake"})
        if pending.error is not None:
            raise pending.error
        return pending.outputs, pending.queue_ns, pending.leader

    def _cancel_pending(self, pending: _Pending) -> None:
        """CancelToken wakeup for one waiter. Still queued: the member
        is removed from its bucket (never reaches the device) —
        stage "queue". Already dispatched: the in-flight fused XLA
        call is NOT re-padded or interrupted; the member is marked
        done with a CANCELLED error and PR-12's per-member early
        completion (_wake_ready/_scatter/_finish all skip event-set
        members) guarantees its slice is never fetched or encoded —
        stage "execute", and _finish bills its share of the batch's
        compute as wasted."""
        with self._cv:
            if pending.event.is_set():
                return  # completed (or expired/shed) before the signal
            bucket = self._buckets.get(pending.shape_key)
            removed = bucket is not None and bucket.remove(pending)
            if removed:
                if not bucket.queues:
                    del self._buckets[pending.shape_key]
                self._drop_accounting_locked(pending)
                stage = "queue"
            else:
                stage = "execute"
            pending.queue_ns = time.monotonic_ns() - pending.enqueue_ns
            pending.error = cancel_mod.cancelled_error(
                "request for model '%s' cancelled %s"
                % (getattr(self._model, "name", "?"),
                   "in queue" if removed else "while executing"),
                stage)
            pending.event.set()
            self._cv.notify_all()

    # -- queue policy -----------------------------------------------------

    def _priority_for(self, params: dict) -> int:
        """Coerced, validated priority class of one request (0 when the
        model has no priority levels). Raises INVALID_ARGUMENT for
        out-of-range or non-numeric values — the silent-drop fix."""
        if not self._priority_levels:
            return 0
        return coerce_priority(params.get("priority"),
                               self._priority_levels,
                               self._default_priority)

    def _timeout_ns_for(self, params: dict, priority: int = 0) -> int:
        """Effective queue timeout for one request: the per-request
        `timeout` parameter (microseconds, KServe-v2) when overrides
        are allowed, else the priority class's default_timeout_us
        (ModelQueuePolicy override), else the model's
        default_queue_policy_timeout_us; 0 = no deadline. String and
        double wire forms are coerced like `priority`."""
        timeout_ns = self._default_timeout_ns
        policy = self._priority_policies.get(priority)
        if policy and policy.get("default_timeout_us"):
            timeout_ns = int(policy["default_timeout_us"]) * NANOS_PER_US
        if self._allow_timeout_override:
            override = params.get("timeout")
            if override is not None:
                try:
                    timeout_ns = max(coerce_int(override), 0) \
                        * NANOS_PER_US
                except (TypeError, ValueError):
                    pass  # malformed timeouts fall back to the default
        return timeout_ns

    def _admit_locked(self, pending: _Pending) -> None:
        """Queue-policy admission for one request (caller holds the
        lock). Four gates, cheapest first:

        0. Autoscale shed directive — while the controller says the
           SLO is unmeetable at max scale, lowest-class arrivals shed
           at the door regardless of queue depth, carrying the
           controller's predicted-recovery Retry-After.
        1. Per-priority max_queue_size (ModelQueuePolicy override) —
           a class over its own bound is rejected even when the global
           queue has room, so one class cannot monopolize the queue.
        2. Shed watermark — past ``shed_watermark * max_queue_size``,
           arrivals of the LOWEST class are shed with Retry-After
           (they would otherwise ride the queue to the hard cap and
           blow every deadline together).
        3. Global max_queue_size — at a hard-full queue, an arrival
           with strictly higher priority than the lowest-priority
           waiter displaces the newest such waiter (the displaced
           request is shed UNAVAILABLE); otherwise the arrival itself
           is rejected. This is what keeps priority-1 goodput at 100%
           while bulk saturates the queue."""
        priority = pending.priority
        directive = self._shed_directive
        if (directive is not None and directive.active
                and self._priority_levels
                and priority == self._priority_levels):
            # Gate 0 — controller-ordered shed: the autoscale loop
            # determined the SLO is unmeetable even at max scale, so
            # lowest-class arrivals shed immediately (not at the
            # watermark) with the controller's predicted recovery as
            # the Retry-After.
            self._hook(self._shed_hook, priority)
            error = self._over_capacity_error(
                "shed by autoscale directive (%s)"
                % (directive.reason or "slo unmeetable at max scale"))
            error.retry_after_s = max(directive.retry_after_s, 0.05)
            raise error
        policy = self._priority_policies.get(priority)
        if policy and policy.get("max_queue_size"):
            if self._pending_by_priority.get(priority, 0) \
                    >= int(policy["max_queue_size"]):
                self._hook(self._reject_hook, priority)
                raise self._over_capacity_error(
                    "priority-%d queue is full (per-priority "
                    "max_queue_size %d)"
                    % (priority, int(policy["max_queue_size"])))
        if self._max_queue_size > 0:
            if (self._shed_watermark > 0 and self._priority_levels
                    and priority == self._priority_levels
                    and self._pending_total
                    >= self._shed_watermark * self._max_queue_size):
                self._hook(self._shed_hook, priority)
                raise self._over_capacity_error(
                    "shed at watermark (queue depth %d >= %.0f%% of "
                    "max_queue_size %d)"
                    % (self._pending_total, self._shed_watermark * 100,
                       self._max_queue_size))
            if self._pending_total >= self._max_queue_size:
                if self._priority_levels \
                        and self._displace_locked(priority):
                    return  # a lower-priority waiter made room
                self._hook(self._reject_hook, priority)
                raise self._over_capacity_error(
                    "exceeds max_queue_size %d" % self._max_queue_size)

    def _displace_locked(self, below: int) -> bool:
        """Sheds the NEWEST waiter of the lowest-priority class whose
        level is strictly greater (= lower priority) than ``below``;
        the PR-2 expiry machinery's removal path reused for overload.
        The newest waiter is chosen because it has the least queue
        time invested — shedding the oldest would maximize wasted
        wait. Returns False when every waiter is at least ``below``."""
        victim: Optional[_Pending] = None
        victim_key = None
        for shape_key, bucket in self._buckets.items():
            for level in reversed(bucket.queues):
                if level <= below:
                    break  # ascending keys: nothing lower-priority left
                candidate = bucket.queues[level][-1]
                if victim is None or level > victim.priority or (
                        level == victim.priority
                        and candidate.enqueue_ns > victim.enqueue_ns):
                    victim = candidate
                    victim_key = shape_key
                break  # only the lowest class of this bucket matters
        if victim is None:
            return False
        bucket = self._buckets[victim_key]
        bucket.remove(victim)
        if not bucket.queues:
            del self._buckets[victim_key]
        self._drop_accounting_locked(victim)
        victim.queue_ns = time.monotonic_ns() - victim.enqueue_ns
        victim.error = self._over_capacity_error(
            "shed for a priority-%d arrival at a full queue "
            "(max_queue_size %d)" % (below, self._max_queue_size))
        victim.event.set()
        self._hook(self._shed_hook, victim.priority)
        return True

    def _drop_accounting_locked(self, pending: _Pending) -> None:
        self._pending_total -= 1
        if self._priority_levels:
            count = self._pending_by_priority.get(pending.priority, 0)
            if count > 1:
                self._pending_by_priority[pending.priority] = count - 1
            else:
                self._pending_by_priority.pop(pending.priority, None)

    def _over_capacity_error(self, detail: str) -> InferenceServerException:
        error = InferenceServerException(
            "request for model '%s' rejected: %s"
            % (getattr(self._model, "name", "?"), detail),
            status="UNAVAILABLE")
        # Server-advised backoff: half the current gather window is a
        # decent guess at when a dispatch will have freed queue room.
        error.retry_after_s = max(
            self._cur_delay_ns / 2 / 1e9, 0.05)
        return error

    @staticmethod
    def _hook(hook: Optional[Callable[..., None]],
              priority: int) -> None:
        # Arity is decided by signature, not by catching TypeError
        # from the call — a hook whose BODY raises TypeError must not
        # be silently re-invoked (side effects would double).
        if hook is None:
            return
        try:
            takes_priority = bool(inspect.signature(hook).parameters)
        except (TypeError, ValueError):  # C callables: no signature
            takes_priority = True
        try:
            if takes_priority:
                hook(priority)
            else:
                hook()  # pre-QoS hooks take no priority argument
        except Exception:  # noqa: BLE001 — stats only
            pass

    def _expire_locked(self, now: int) -> Optional[int]:
        """Drops deadline-passed requests (timeout_action REJECT) and
        returns the earliest live deadline for the gather wake-up, or
        None when nothing is deadlined. Caller holds the lock. Expiry
        runs BEFORE bucket selection so an expired request never
        reaches the device; deadlines are void while draining on stop
        (stop() promises execution)."""
        if self._stopping or not self._timeout_reject \
                or not self._any_deadlines:
            return None
        earliest: Optional[int] = None
        expired: List[_Pending] = []
        for shape_key in list(self._buckets):
            bucket = self._buckets[shape_key]
            for level in list(bucket.queues):
                queue = bucket.queues[level]
                live = []
                for pending in queue:
                    if pending.deadline_ns and now >= pending.deadline_ns:
                        pending.queue_ns = now - pending.enqueue_ns
                        expired.append(pending)
                        continue
                    if pending.deadline_ns:
                        if earliest is None \
                                or pending.deadline_ns < earliest:
                            earliest = pending.deadline_ns
                    live.append(pending)
                if len(live) != len(queue):
                    if live:
                        queue[:] = live
                    else:
                        del bucket.queues[level]
            if not bucket.queues:
                del self._buckets[shape_key]
        for pending in expired:
            self._drop_accounting_locked(pending)
            pending.error = InferenceServerException(
                "request for model '%s' timed out in queue after "
                "%d us" % (getattr(self._model, "name", "?"),
                           pending.queue_ns // NANOS_PER_US),
                status="DEADLINE_EXCEEDED")
            pending.event.set()
            self._hook(self._timeout_hook, pending.priority)
        return earliest

    # -- adaptive delay ---------------------------------------------------

    def _adaptive_delay_ns(self) -> int:
        """Gather-window size for the current arrival rate (caller
        holds the lock). Sized so a full preferred batch has time to
        accumulate — but only for models that opted into adaptation
        (set delay bounds) AND declared preferred sizes, and only when
        arrivals are frequent enough that waiting can plausibly fill
        one. The idle-gap cutoff in _take_ready_bucket keeps the
        stretched window from taxing bounded closed-loop traffic."""
        ema = self._ia_ema_ns
        if not self._adaptive or not self._preferred \
                or self._preferred[-1] <= 1 or ema <= 0:
            delay = self._delay_ns
            return int(min(max(delay, self._delay_min_ns),
                           self._delay_max_ns))
        target = ema * (self._preferred[-1] - 1)
        target = min(max(target, self._delay_min_ns), self._delay_max_ns)
        # Taper toward the floor as traffic thins instead of cliffing:
        # `g` is how many arrivals the longest allowed window can
        # plausibly catch. At g<=2 waiting cannot form a batch (floor);
        # at g>=4 the full target applies; linear in between, so the
        # window doesn't oscillate when the rate hovers at a boundary.
        g = self._delay_max_ns / ema
        if g <= 2:
            delay = self._delay_min_ns
        elif g < 4:
            delay = self._delay_min_ns + \
                (target - self._delay_min_ns) * (g - 2) / 2
        else:
            delay = target
        return int(min(max(delay, self._delay_min_ns), self._delay_max_ns))

    def _idle_cutoff_ns(self, delay_ns: int) -> int:
        """How long the arrival stream may stall before a partial
        bucket dispatches early (caller holds the lock). Bounded-
        concurrency closed loops stop producing once every client is
        queued — detecting the stalled stream and dispatching beats
        burning the rest of a window sized for traffic that cannot
        arrive. Never below delay_min (the configured latency floor)."""
        ema = int(self._ia_ema_ns)
        if ema <= 0:
            return delay_ns
        return min(max(4 * ema, self._delay_min_ns), delay_ns)

    # -- gather thread ---------------------------------------------------

    def _gather_loop(self):
        while True:
            bucket: Optional[List[_Pending]] = None
            with self._cv:
                while bucket is None:
                    if self._stopping and not self._buckets:
                        return
                    if self._inflight >= self._depth:
                        # Pipeline full: woken by a batch completion —
                        # but queued deadlines must still expire, so
                        # sleep only until the earliest one.
                        wake = self._expire_locked(time.monotonic_ns())
                        if wake is None:
                            self._cv.wait()
                        else:
                            self._cv.wait(timeout=max(
                                wake - time.monotonic_ns(), 0) / 1e9)
                        continue
                    now = time.monotonic_ns()
                    bucket, wake_ns = self._take_ready_bucket(now)
                    if bucket is not None:
                        break
                    if not self._buckets:
                        self._cv.wait()
                    else:
                        self._cv.wait(
                            timeout=max(wake_ns - now, 0) / 1e9)
                self._inflight += 1
            try:
                self._exec_pool.submit(self._execute, bucket)
            except RuntimeError:  # pool shut down mid-stop
                self._execute(bucket)

    def _take_ready_bucket(self, now: int):
        """Pops and returns the ready bucket with the OLDEST head
        request (full to the largest preferred size / max batch, past
        its adaptive deadline, past the idle-gap cutoff, or draining
        on stop); otherwise (None, earliest_wake_ns). Oldest-head
        order keeps a flooded shape from starving a rare shape whose
        deadline expired while the flood's queue stayed permanently
        full. Within the chosen bucket the take respects priority
        order (class 1 fills first, bulk rides the remaining
        capacity), with an aged-oldest slot every AGE_EVERY dispatches
        so strict ordering cannot starve bulk. Caller holds the
        lock."""
        expire_wake = self._expire_locked(now)
        if not self._buckets:
            return None, expire_wake
        self._cur_delay_ns = delay = self._adaptive_delay_ns()
        full_at = self._preferred[-1] if self._preferred else self._max_batch
        # Arrival stream stalled (bounded closed loop fully queued):
        # partial buckets dispatch now instead of waiting out a window
        # sized for arrivals that cannot come.
        stalled = (self._last_arrival_ns > 0 and
                   now - self._last_arrival_ns >= self._idle_cutoff_ns(delay))
        ready_key = None
        ready_head = None
        earliest: Optional[int] = None
        for shape_key, bucket_q in self._buckets.items():
            total = bucket_q.plan(self._max_batch, full_at)
            head_ns = bucket_q.head_ns()
            deadline = head_ns + delay
            if (total >= full_at or now >= deadline or stalled
                    or self._stopping):
                if ready_head is None or head_ns < ready_head:
                    ready_key, ready_head = shape_key, head_ns
                continue
            wake = min(deadline,
                       self._last_arrival_ns + self._idle_cutoff_ns(delay))
            if earliest is None or wake < earliest:
                earliest = wake
        if expire_wake is not None and (earliest is None
                                        or expire_wake < earliest):
            # Queue-policy deadlines must wake the gather thread even
            # when every bucket's dispatch deadline lies further out.
            earliest = expire_wake
        if ready_key is not None:
            bucket_q = self._buckets[ready_key]
            bucket_q.dispatches += 1
            age_oldest = (self._priority_levels > 0
                          and bucket_q.dispatches % self.AGE_EVERY == 0)
            taken = bucket_q.take(self._max_batch, full_at,
                                  age_oldest=age_oldest)
            for pending in taken:
                self._drop_accounting_locked(pending)
            if not bucket_q.queues:
                del self._buckets[ready_key]
            return taken, None
        return None, earliest

    def _padded_size(self, total: int) -> int:
        """Rounds the fused batch up to a stable compile shape: the
        smallest preferred size that fits, else the next power of two
        (capped at max_batch). XLA traces once per shape — unpadded
        fusing would recompile for every distinct request mix."""
        for size in self._preferred:
            if total <= size:
                return size
        if total >= self._max_batch:
            return self._max_batch
        size = 1
        while size < total:
            size <<= 1
        return min(size, self._max_batch)

    # -- execution stage (exec pool) --------------------------------------

    def _execute(self, bucket: List[_Pending]):
        start_ns = time.monotonic_ns()
        bucket[0].leader = True
        traced = [p.trace for p in bucket if p.trace is not None]
        for pending in bucket:
            pending.queue_ns = start_ns - pending.enqueue_ns
            if pending.trace is not None:
                # The priority attribute makes QoS ordering visible in
                # the span tree: a reader can see a priority-1 queue
                # span end (dispatch) while older bulk spans run on.
                pending.trace.add_timed(
                    spantrace.SPAN_QUEUE,
                    pending.queue_from_ns or pending.enqueue_ns, start_ns,
                    {"priority": pending.priority} if pending.priority
                    else None)
        try:
            total = sum(p.batch for p in bucket)
            target = self._padded_size(total)
            passthrough = len(bucket) == 1 and bucket[0].batch == target
            self._tracker.enter_compute()
            try:
                scope = (self._compile_scope(
                             getattr(self._model, "name", "?"),
                             "b%d" % target)
                         if self._compile_scope is not None
                         else contextlib.nullcontext())
                with scope:
                    if passthrough:
                        outputs = self._target.infer(
                            bucket[0].inputs, bucket[0].params)
                    else:
                        fused = {
                            name: _fuse_chunks(
                                [p.inputs[name] for p in bucket],
                                target, total)
                            for name in bucket[0].inputs
                        }
                        outputs = self._target.infer(
                            fused, bucket[0].params)
            finally:
                self._tracker.exit_compute()
            compute_end_ns = time.monotonic_ns()
            compute_ns = compute_end_ns - start_ns
            if traced:
                # ONE batch-execution span shared by every sampled
                # member: same span id in each trace, carrying the
                # fused batch size and compile bucket — the reader
                # both attributes the time per request and sees the
                # work was done once. Its end bound is reused as the
                # fetch chain's start so no slice between the stages
                # goes untracked.
                batch_span = spantrace.shared_span(
                    spantrace.SPAN_BATCH_EXECUTE, start_ns,
                    compute_end_ns,
                    {"batch": total, "padded_batch": target,
                     "requests": len(bucket)})
                for trace in traced:
                    trace.add(batch_span)
            if passthrough:
                bucket[0].outputs = outputs
                self._finish(bucket, target, compute_ns, 0,
                             done_from=compute_end_ns)
                return
            # Partition the bucket by where each member wants its
            # slice to live. Explicit device_outputs wins; None falls
            # back to the input-type heuristic (wire requests decode
            # to numpy, the TPU-shm path resolves device arrays) —
            # the pre-dataflow behavior, member by member.
            device_members = [
                p for p in bucket
                if p.device_outputs or (
                    p.device_outputs is None
                    and any(not isinstance(p.inputs[name], np.ndarray)
                            for name in p.inputs))
            ]
            if device_members and len(device_members) < len(bucket):
                # Mixed ensemble-interior + wire bucket (the fusion the
                # dataflow exists to create): device consumers wake NOW
                # with device slices — zero host round-trip — while the
                # host riders share one batched fetch below. _scatter /
                # _wake_ready / _finish all skip already-set members.
                offset = 0
                for pending in bucket:
                    if pending in device_members:
                        pending.outputs = {
                            name: array[offset:offset + pending.batch]
                            for name, array in outputs.items()
                        }
                        pending.done_ns = compute_end_ns
                        pending.event.set()
                    offset += pending.batch
            if len(device_members) < len(bucket):
                # The remaining members arrived over the wire and will
                # be serialized to host bytes anyway: fetch the fused
                # output ONCE (one relay round-trip for the whole
                # bucket, not n slice transfers) — and do it on the
                # fetch pool so this exec worker (and the gather
                # thread) can dispatch the NEXT bucket while this
                # transfer is in flight. The legacy arm kicks its
                # async copies HERE, before even the pool handoff; the
                # overlapped fetcher issues its own in start() AFTER
                # deciding which outputs land chunked (a full-buffer
                # kick would double a chunked tensor's DMA traffic).
                if self._fetcher is None:
                    for array in outputs.values():
                        if hasattr(array, "copy_to_host_async"):
                            array.copy_to_host_async()
                finish = (self._finish_overlapped
                          if self._fetcher is not None
                          else self._finish_host_bucket)
                try:
                    self._fetch_pool.submit(
                        finish, bucket, outputs, target, compute_ns)
                except RuntimeError:  # pool shut down mid-stop
                    finish(bucket, outputs, target, compute_ns)
            else:
                # Device-resident bucket (TPU-shm path): slices are
                # lazy device views; outputs stay in HBM end-to-end.
                self._scatter(bucket, outputs)
                self._finish(bucket, target, compute_ns, 0,
                             done_from=compute_end_ns)
        except Exception as e:
            # Members already served device slices (mixed bucket) are
            # past the point of failure — error only the unwoken.
            self._assign_error(
                [p for p in bucket if not p.event.is_set()], e)
            self._finish(bucket, 0, 0, 0, ok=False)

    # -- fetch stage (fetch pool) -----------------------------------------

    def _finish_host_bucket(self, bucket: List[_Pending], outputs,
                            target: int, compute_ns: int) -> None:
        fetch_start = time.monotonic_ns()
        self._tracker.enter_fetch()
        # Device consumers in a mixed bucket completed at compute end
        # (event already set): the relay fetch below is not their work,
        # so their traces must not carry relay_fetch spans — that
        # absence IS the dataflow's zero-host-round-trip evidence.
        traced = [p.trace for p in bucket
                  if p.trace is not None and not p.event.is_set()]
        mark_ns = 0
        try:
            if traced:
                # Per-output relay fetch, individually timed: one
                # shared span per output tensor (the whole bucket
                # rides one transfer) — the measured form of ROADMAP
                # item 1's relay_fetch_ms_est. Boundaries chain (each
                # span starts where the previous ended, the first at
                # the pool handoff) so the fetch stage tiles.
                host = {}
                mark_ns = fetch_start
                for name, array in outputs.items():
                    host[name] = np.asarray(array)
                    end_ns = time.monotonic_ns()
                    fetch_span = spantrace.shared_span(
                        spantrace.SPAN_RELAY_FETCH, mark_ns, end_ns,
                        {"output": name,
                         "nbytes": int(host[name].nbytes)})
                    mark_ns = end_ns
                    for trace in traced:
                        trace.add(fetch_span)
            else:
                host = {name: np.asarray(a) for name, a in outputs.items()}
            self._scatter(bucket, host)
        except Exception as e:  # noqa: BLE001 — waiters must wake
            self._assign_error(
                [p for p in bucket if not p.event.is_set()], e)
            self._tracker.exit_fetch()
            self._finish(bucket, 0, 0, 0, ok=False)
            return
        self._tracker.exit_fetch()
        self._finish(bucket, target, compute_ns,
                     time.monotonic_ns() - fetch_start,
                     done_from=mark_ns)

    def _finish_overlapped(self, bucket: List[_Pending], outputs,
                           target: int, compute_ns: int) -> None:
        """Overlapped replacement for _finish_host_bucket
        (client_tpu.server.fetch): every output's device->host
        transfer is issued at once, outputs are processed in LANDING
        order, and each member wakes the moment ITS wanted outputs
        have landed — the first response encodes while the batch's
        remaining tensors are still in flight. One output's failed
        fetch fails only the members that asked for it."""
        fetch_start = time.monotonic_ns()
        self._tracker.enter_fetch()
        # Same exclusion as _finish_host_bucket: members already woken
        # with device slices never see relay_fetch spans.
        traced = [p.trace for p in bucket
                  if p.trace is not None and not p.event.is_set()]
        offsets: List[int] = []
        offset = 0
        for pending in bucket:
            offsets.append(offset)
            offset += pending.batch
        ordered = tuple(outputs)  # model output order, for responses
        landed: Dict[str, np.ndarray] = {}
        failed: Dict[str, Exception] = {}
        mark_ns = fetch_start
        try:
            inflight = self._fetcher.start(outputs)
            for handle in inflight.as_completed():
                end_ns = time.monotonic_ns()
                if handle.error is not None:
                    failed[handle.name] = handle.error
                else:
                    landed[handle.name] = handle.value
                    if traced:
                        # Same shared relay_fetch span the legacy path
                        # records, with the wait bounded by landing
                        # order instead of transfer order; `mode` and
                        # `chunks` make the overlap visible to a span
                        # reader.
                        attrs = {"output": handle.name,
                                 "nbytes": int(handle.value.nbytes),
                                 "mode": "overlap"}
                        if handle.chunks:
                            attrs["chunks"] = handle.chunks
                        fetch_span = spantrace.shared_span(
                            spantrace.SPAN_RELAY_FETCH, mark_ns,
                            end_ns, attrs)
                        for trace in traced:
                            trace.add(fetch_span)
                mark_ns = end_ns
                self._wake_ready(bucket, offsets, ordered, landed,
                                 failed, end_ns)
        except Exception as e:  # noqa: BLE001 — waiters must wake
            self._assign_error(
                [p for p in bucket if not p.event.is_set()], e)
            self._tracker.exit_fetch()
            self._finish(bucket, 0, 0, 0, ok=False)
            return
        self._tracker.exit_fetch()
        # Final sweep: members wanting ALL outputs when some failed,
        # and members whose wanted set resolved empty.
        self._wake_ready(bucket, offsets, ordered, landed, failed,
                         mark_ns, final=True)
        # ok=True even on a partial fetch failure: the execution
        # happened and members that didn't want the failed output were
        # served — stats/telemetry must record the batch (only the
        # failed members' errors are per-member, via _wake_ready).
        self._finish(bucket, target, compute_ns,
                     time.monotonic_ns() - fetch_start,
                     done_from=mark_ns)

    @staticmethod
    def _wake_ready(bucket: List[_Pending], offsets: List[int],
                    ordered: tuple, landed: Dict[str, np.ndarray],
                    failed: Dict[str, Exception], done_ns: int,
                    final: bool = False) -> None:
        """Per-member early completion: wake every not-yet-woken
        member whose wanted outputs have all landed (its outputs dict
        holds just those slices, in model output order), or whose
        wanted outputs include a failed fetch (only those members see
        the error). A member wanting everything (wanted=None)
        completes on the last landing — or errors on the final sweep
        if anything failed."""
        names = frozenset(ordered)
        for pending, offset in zip(bucket, offsets):
            if pending.event.is_set():
                continue
            wanted = (names if pending.wanted is None
                      else pending.wanted & names)
            hit = (failed.keys() & wanted if pending.wanted is not None
                   else (failed.keys() if final else frozenset()))
            if hit:
                error = failed[sorted(hit)[0]]
                if not isinstance(error, InferenceServerException):
                    error = InferenceServerException(
                        "output fetch failed for '%s': %s"
                        % (sorted(hit)[0], error), status="INTERNAL")
                pending.error = error
                pending.done_ns = done_ns
                pending.event.set()
                continue
            if wanted <= landed.keys():
                pending.outputs = {
                    name: landed[name][offset:offset + pending.batch]
                    for name in ordered if name in wanted
                }
                pending.done_ns = done_ns
                pending.event.set()

    def _finish(self, bucket: List[_Pending], executed: int,
                compute_ns: int, fetch_ns: int, ok: bool = True,
                done_from: int = 0) -> None:
        """Completion for one fused batch: wake the waiters, record the
        execution, release the pipeline slot. ``done_from`` chains the
        wake-span base off the last compute/fetch boundary so the
        scatter/notify slice is attributed too."""
        done_ns = done_from or time.monotonic_ns()
        for pending in bucket:
            if pending.event.is_set():
                continue  # woken early (per-member completion)
            pending.done_ns = done_ns
            pending.event.set()
        if ok and self._stats_hook is not None:
            try:
                self._stats_hook(executed, compute_ns, fetch_ns)
            except Exception:  # noqa: BLE001 — stats never fail serving
                pass
        if ok and self._wasted_hook is not None and compute_ns \
                and executed:
            # Members cancelled AFTER dispatch (stage "execute") rode
            # the fused call to completion but nobody reads their
            # slice: bill their row-proportional share of the batch's
            # device time as wasted compute.
            wasted_ns = sum(
                compute_ns * p.batch // executed for p in bucket
                if getattr(p.error, "cancel_stage", None) == "execute")
            if wasted_ns:
                try:
                    self._wasted_hook(wasted_ns)
                except Exception:  # noqa: BLE001 — stats never fail
                    pass  # serving
        if ok and self._telemetry is not None \
                and self._telemetry.enabled and compute_ns:
            try:
                # First SAMPLED member only: flight scratch traces
                # (sampled=False) are usually discarded and must not
                # stamp exemplars (spantrace.exemplar_id).
                trace_id = next(
                    (tid for tid in (spantrace.exemplar_id(p.trace)
                                     for p in bucket)
                     if tid is not None), None)
                name = getattr(self._model, "name", "?")
                self._telemetry.observe_stage(
                    name, "batch_execute", compute_ns / 1000.0,
                    trace_id)
                if fetch_ns:
                    self._telemetry.observe_stage(
                        name, "relay_fetch", fetch_ns / 1000.0,
                        trace_id)
            except Exception:  # noqa: BLE001 — telemetry never fails
                pass  # serving
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    @staticmethod
    def _scatter(bucket: List[_Pending], outputs) -> None:
        offset = 0
        for pending in bucket:
            if not pending.event.is_set():
                # Already-woken members (mixed bucket's device
                # consumers) hold device slices; overwriting them here
                # would race their reader.
                pending.outputs = {
                    name: array[offset:offset + pending.batch]
                    for name, array in outputs.items()
                }
            offset += pending.batch

    @staticmethod
    def _assign_error(bucket: List[_Pending], e: Exception) -> None:
        error = e if isinstance(e, InferenceServerException) else \
            InferenceServerException(
                "batched inference failed: %s" % e, status="INTERNAL")
        for pending in bucket:
            pending.error = error

    # -- observability ----------------------------------------------------

    def stats_snapshot(self) -> dict:
        """Point-in-time pipeline gauges plus cumulative compute/fetch
        overlap counters (the statistics endpoints' pipeline_stats).
        ``pending_by_priority`` feeds the tpu_priority_queue_size
        Prometheus family (empty when priority levels are off)."""
        with self._cv:
            pending = self._pending_total
            inflight = self._inflight
            delay_us = self._cur_delay_ns // NANOS_PER_US
            # Every configured class reports a row (0 included):
            # a class's series must not appear/disappear with traffic.
            by_priority = {
                level: self._pending_by_priority.get(level, 0)
                for level in range(1, self._priority_levels + 1)
            }
        compute_ns, fetch_ns, overlap_ns = self._tracker.snapshot()
        return {
            "pending_count": pending,
            "inflight_count": inflight,
            "queue_delay_us": delay_us,
            "compute_ns": compute_ns,
            "fetch_ns": fetch_ns,
            "overlap_ns": overlap_ns,
            "overlap_ratio": (overlap_ns / fetch_ns) if fetch_ns else 0.0,
            "pending_by_priority": by_priority,
        }

    def set_shed_directive(self, directive) -> None:
        """Installs/clears the controller's shed order (a
        qos.ShedDirective or None). Reference assignment only — the
        admission path reads it without extra locking, so a directive
        object is never mutated after install (the controller swaps
        in a fresh instance per decision)."""
        with self._cv:
            self._shed_directive = directive

    def shed_directive(self):
        """The active qos.ShedDirective, or None (for /v2/debug)."""
        return self._shed_directive

    def debug_snapshot(self) -> dict:
        """The /v2/debug queue view: per-shape-bucket depth segmented
        per priority class, plus the oldest waiter's age per bucket —
        the granularity stats_snapshot's totals flatten away. Bucket
        keys are shape fingerprints (bounded by the traffic's distinct
        shapes, not by request count)."""
        now_ns = time.monotonic_ns()
        with self._cv:
            buckets = {}
            for shape_key, bucket in self._buckets.items():
                by_priority = {
                    str(level): len(queue)
                    for level, queue in bucket.queues.items()
                }
                depth = sum(by_priority.values())
                if not depth:
                    continue
                buckets[str(shape_key)] = {
                    "pending": depth,
                    "by_priority": by_priority,
                    "oldest_wait_us":
                        max(now_ns - bucket.head_ns(), 0) // 1000,
                }
            return {
                "pending_count": self._pending_total,
                "inflight_count": self._inflight,
                "max_queue_size": self._max_queue_size,
                "queue_delay_us": self._cur_delay_ns // NANOS_PER_US,
                "buckets": buckets,
            }


def _fuse_chunks(chunks, target: int, total: int):
    """Assembles per-request input chunks into one batch of `target`
    rows (unfilled pad rows stay zero; they are computed and
    discarded).

    When any chunk is a device array (the TPU-shm path resolves
    inputs to ``jax.Array``s), fusion runs as device ops — a numpy
    concat here would silently drag every chunk back to host, defeating
    the arena's zero-copy design (the round-2 12-infer/s regression).
    The device path writes chunks into a zero buffer with
    ``dynamic_update_slice`` — start offsets are runtime values, so XLA
    compiles ONE kernel per (buffer, chunk) shape pair instead of one
    ``concatenate`` per distinct chunk-count/pad mix (the round-3
    steady-state recompile source)."""
    all_host = all(isinstance(c, np.ndarray) for c in chunks)
    if all_host:
        if target > total:
            pad_shape = (target - total,) + tuple(chunks[-1].shape[1:])
            if chunks[-1].dtype.kind == "O":  # BYTES: pad rows need
                pad = np.broadcast_to(  # valid payloads, not int 0
                    chunks[-1][-1:], pad_shape)
            else:
                pad = np.zeros(pad_shape, dtype=chunks[-1].dtype)
            chunks = chunks + [pad]
        return np.concatenate(chunks, axis=0)
    import jax
    import jax.numpy as jnp

    first = chunks[0]
    buf = jnp.zeros((target,) + tuple(first.shape[1:]), dtype=first.dtype)
    # np.int32 offsets are runtime arguments to the cached executable,
    # never baked-in constants — one compile per shape pair, period.
    zeros = (np.int32(0),) * (buf.ndim - 1)
    offset = 0
    for chunk in chunks:
        buf = jax.lax.dynamic_update_slice(
            buf, chunk, (np.int32(offset),) + zeros)
        offset += int(chunk.shape[0])
    return buf


# Parameters enforced per request by the scheduler itself, never by
# the model: they must not fragment fusion. `timeout` (PR 2) is a
# per-request deadline; `priority` orders dispatch but the fused batch
# executes identically; `tenant` is admission-control identity;
# `cancel_token` is the request's CancelToken riding params into the
# decoupled stream path — per-request lifecycle, never batch identity.
_QOS_PARAMS = frozenset(("timeout", "priority", "tenant",
                         "cancel_token"))


def _params_fingerprint(params: dict):
    """Normalized, hashable view of request parameters. Requests are
    only fused when their parameters match — fusing would otherwise
    execute the whole bucket with the leader's params, silently
    dropping the rest (custom params). QoS knobs (`timeout`,
    `priority`, `tenant`) are excluded: the scheduler enforces them
    per request, so mixed deadlines/classes/tenants still fuse into
    one padded execution — QoS ordering costs dispatch order, not
    batch efficiency."""
    if not params:
        return ()
    return tuple(
        (key, repr(params[key])) for key in sorted(params)
        if key not in _QOS_PARAMS
    )


def wants_dynamic_batching(model) -> bool:
    return (
        getattr(model, "dynamic_batching", False)
        and int(getattr(model, "max_batch_size", 0)) > 1
        and not getattr(model, "decoupled", False)
    )
