"""Sharded serving across the mesh: tensor-parallel slices as a
first-class replica axis (ROADMAP item 3, docs/sharded_serving.md).

A model that only exists sharded (weights partitioned over a
``shard_mesh``, e.g. ``{"tp": 4}``) is served by the PR-8 ReplicaSet
exactly like a per-device model — except each replica is a **mesh
slice**: a disjoint set of ``slice_width`` devices carrying one
sharded executable plus that slice's shard of the weights. The
``instance_group`` count stays the replica axis (2 replicas x tp=4 =
8 devices); this module owns everything slice-shaped so the router
keeps its device-agnostic health/routing math:

* **Planning.** :func:`plan_slice` deterministically partitions the
  local device list into contiguous ``slice_width`` blocks (replica
  index -> device block, wrapping when indexes outlive the device
  count — index reuse after scale churn must not strand hardware).
* **Construction.** :func:`build_instance` calls the model factory
  with the slice's ``jax.Mesh`` when the factory accepts a ``mesh``
  keyword — the contract a sharded model opts into; factories without
  the keyword degrade to unsharded instances (served, but warned).
* **Admission.** :func:`admit_slice` books the slice's weights with
  the PR-18 HBM allocator as **per-participating-device rows**
  (``slice:<index>:<device>`` components, real per-device shard bytes
  from ``addressable_shards`` when available): admission runs under
  each member device's arbitration mutex, so a slice-unit scale-up
  contends with every other allocation on every member chip — and
  ``tpu_hbm_model_bytes`` / ``/v2/debug`` stay truthful under tp>1
  instead of attributing the whole slab to device 0.

Fault domains widen with the slice: the ReplicaSet attributes
watchdog/breaker evidence to every member device, chaos ``device=<id>``
targeting fails a slice through any one chip, and autoscale
scale_up/scale_down operates in slice units (one resize = one whole
slice's devices + leases + ledger rows).
"""

from __future__ import annotations

import inspect
import logging
import threading
from typing import Callable, List, Optional, Sequence, Tuple

_LOG = logging.getLogger("client_tpu.server.mesh")

# Axis-name order for rendering/parsing sanity; anything the parallel
# helpers accept is allowed — these are just the conventional names.
KNOWN_AXES = ("dp", "fsdp", "tp", "sp", "ep", "pp")


def parse_shard_mesh(spec) -> List[Tuple[str, int]]:
    """Normalizes a shard-mesh spec to an ordered axis list.

    Accepts a dict (``{"tp": 4}``), an iterable of ``(axis, size)``
    pairs, or a spec string (``"tp=4"`` / ``"sp=2,tp=2"``). Axes with
    size <= 1 are dropped (they shard nothing). Returns ``[]`` for an
    empty/None spec."""
    if not spec:
        return []
    if isinstance(spec, str):
        pairs = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            axis, sep, size = part.partition("=")
            if not sep:
                raise ValueError(
                    "shard_mesh entry '%s' is not axis=size" % part)
            pairs.append((axis.strip(), int(size)))
    elif isinstance(spec, dict):
        pairs = [(str(axis), int(size)) for axis, size in spec.items()]
    else:
        pairs = [(str(axis), int(size)) for axis, size in spec]
    return [(axis, size) for axis, size in pairs if size > 1]


def shard_axes(model) -> List[Tuple[str, int]]:
    """The model's declared shard-mesh axes (``[]`` = unsharded)."""
    return parse_shard_mesh(getattr(model, "shard_mesh", None))


def wants_mesh(model) -> bool:
    """A model opts into slice serving by declaring a ``shard_mesh``
    whose axis product exceeds one device."""
    return bool(shard_axes(model))


def slice_width(model) -> int:
    """Devices per slice: the product of the shard-mesh axis sizes."""
    width = 1
    for _axis, size in shard_axes(model):
        width *= size
    return width


def _local_devices():
    import jax

    return jax.devices()


class MeshSlice:
    """One replica-sized fault domain: ``slice_width`` devices plus
    the ``jax.Mesh`` the slice's executable is pjit-ed over."""

    __slots__ = ("slice_id", "axes", "devices", "device_ids",
                 "device_keys", "mesh")

    def __init__(self, slice_id: int, axes: Sequence[Tuple[str, int]],
                 devices):
        from client_tpu.parallel import create_mesh

        self.slice_id = int(slice_id)
        self.axes = list(axes)
        self.devices = list(devices)
        self.device_ids = tuple(int(d.id) for d in self.devices)
        self.device_keys = tuple("%s-%d" % (d.platform.upper(), d.id)
                                 for d in self.devices)
        self.mesh = create_mesh(self.axes, devices=self.devices)

    def describe(self) -> str:
        return "slice %d [%s] over devices %s" % (
            self.slice_id,
            ",".join("%s=%d" % (a, s) for a, s in self.axes),
            list(self.device_ids))


def plan_slice(axes: Sequence[Tuple[str, int]], slice_id: int,
               devices=None) -> MeshSlice:
    """Deterministic replica-index -> device-block assignment:
    contiguous ``width`` blocks of the local device list, wrapping
    modulo the device count. Replica indexes are never reused across
    resizes (ReplicaSet semantics), so a long-lived fleet's index 37
    must still land on real hardware — the wrap keeps the mapping
    total while preserving "disjoint blocks" whenever
    ``count * width <= len(devices)``."""
    devices = list(devices) if devices is not None else _local_devices()
    width = 1
    for _axis, size in axes:
        width *= size
    if width > len(devices):
        raise ValueError(
            "shard_mesh wants %d devices per slice but only %d are "
            "visible" % (width, len(devices)))
    start = (int(slice_id) * width) % len(devices)
    block = [devices[(start + i) % len(devices)] for i in range(width)]
    return MeshSlice(slice_id, axes, block)


def build_instance(factory: Optional[Callable], mesh_slice: MeshSlice):
    """Instantiates one slice's sharded executable: calls ``factory``
    with ``mesh=<slice mesh>`` when its signature accepts it (the
    sharded-model factory contract), else calls it bare and serves the
    unsharded instance with a warning — a misdeclared model degrades
    to PR-8 behavior instead of failing the fleet."""
    if factory is None:
        return None
    if _accepts_mesh(factory):
        return factory(mesh=mesh_slice.mesh)
    _LOG.warning(
        "model factory for %s does not accept a mesh= keyword; the "
        "slice serves an UNSHARDED instance", mesh_slice.describe())
    return factory()


def _accepts_mesh(factory: Callable) -> bool:
    try:
        signature = inspect.signature(factory)
    except (TypeError, ValueError):
        return False
    for param in signature.parameters.values():
        if param.kind == inspect.Parameter.VAR_KEYWORD:
            return True
        if param.name == "mesh" and param.kind in (
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
                inspect.Parameter.KEYWORD_ONLY):
            return True
    return False


class SliceResources:
    """The per-device HBM leases backing one slice's weights. Released
    exactly once (idempotent, like the leases themselves) by the
    ReplicaSet when the slice leaves routing — scale-down drain,
    supervisor re-initialization, or set teardown."""

    __slots__ = ("leases", "_lock")

    def __init__(self):
        self.leases: List = []
        self._lock = threading.Lock()

    def release(self) -> None:
        with self._lock:
            leases, self.leases = self.leases, []
        if not leases:
            return
        try:
            from client_tpu.server import hbm

            allocator = hbm.get()
        except Exception:  # noqa: BLE001 — accounting must never
            return  # block teardown
        for lease in leases:
            allocator.release(lease)


def per_device_bytes(instance, mesh_slice: MeshSlice) -> dict:
    """device_key -> resident weight bytes for this slice's instance.

    Sums real per-shard bytes from each ``jax.Array``'s addressable
    shards when the arrays are sharded (the honest number under tp>1);
    arrays without shard introspection fall back to an even split of
    their total across the slice — per-device rows stay populated
    either way."""
    from client_tpu.server import devstats as devstats_mod

    width = max(len(mesh_slice.device_keys), 1)
    totals = {key: 0 for key in mesh_slice.device_keys}
    attrs = getattr(instance, "__dict__", None) or {}
    for value in attrs.values():
        for leaf in _array_leaves(value):
            if not _shard_into(leaf, totals):
                share = -(-int(getattr(leaf, "nbytes", 0)) // width)
                for key in totals:
                    totals[key] += share
    if not any(totals.values()):
        # No introspectable arrays (a pure-python stub model): fall
        # back to the aggregate estimate split evenly, so admission
        # still exercises every member device's budget.
        share = -(-devstats_mod.model_array_bytes(instance) // width)
        totals = {key: share for key in mesh_slice.device_keys}
    return totals


def _array_leaves(value):
    try:
        import jax

        leaves = jax.tree.leaves(value)
    except Exception:  # noqa: BLE001 — not a pytree of arrays
        return []
    return [leaf for leaf in leaves
            if hasattr(leaf, "nbytes") and hasattr(leaf, "dtype")]


def _shard_into(leaf, totals: dict) -> bool:
    """Adds ``leaf``'s per-device shard bytes into ``totals``; False
    when the array exposes no shard placement (caller even-splits)."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards:
        return False
    landed = False
    try:
        for shard in shards:
            device = shard.device
            key = "%s-%d" % (device.platform.upper(), device.id)
            if key in totals:
                data = shard.data
                totals[key] += int(getattr(data, "nbytes", 0))
                landed = True
    except Exception:  # noqa: BLE001 — introspection is best-effort
        return landed
    return landed


def admit_slice(model_name: str, mesh_slice: MeshSlice,
                instance, reason: str = "slice_admission"
                ) -> SliceResources:
    """Books the slice's weights with the HBM allocator as one lease
    per participating device (``slice:<id>:<device>`` components —
    each lease registers its own ledger row, so the device axis of
    ``tpu_hbm_model_bytes`` stays truthful under tp>1). Budgeted
    admission runs per device under that device's arbitration mutex —
    the slice-unit scale-up contention point; a device that cannot fit
    its share even after eviction raises the allocator's honest
    retryable deferral, and every already-granted sibling lease rolls
    back."""
    from client_tpu.server import hbm

    allocator = hbm.get()
    plan = per_device_bytes(instance, mesh_slice)
    resources = SliceResources()
    granted: List = []
    try:
        for device_key, nbytes in sorted(plan.items()):
            granted.append(allocator.lease(
                str(model_name),
                "slice:%d:%s" % (mesh_slice.slice_id, device_key),
                nbytes, device_key=device_key, reason=reason))
        resources.leases = [lease for lease in granted
                            if lease is not None]
    finally:
        if not resources.leases:
            # A member device refused its share mid-loop: roll the
            # sibling grants back so a failed slice admission leaves
            # zero phantom pressure on any device.
            for lease in granted:
                if lease is not None:
                    allocator.release(lease)
    return resources
