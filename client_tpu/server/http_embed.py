"""Framework-agnostic KServe-v2 REST dispatch for embedded hosts.

The native HTTP front-end (native/server/http1_server.cc inside
tpu_serverd) terminates HTTP/1.1 in C++ and forwards each request here
as (method, path, headers, body) -> (status, headers, body) — the REST
twin of embed.grpc_call. The route surface mirrors the aiohttp server
(client_tpu/server/http_server.py) except the streaming endpoints —
generate_stream and the OpenAI SSE APIs need chunked responses, so the
aiohttp front-end remains the home for those (non-streaming generate
IS served here).
"""

from __future__ import annotations

import json
import re
from typing import Dict, Optional, Tuple

from google.protobuf import json_format

from client_tpu import status_map
from client_tpu.protocol.http_wire import (
    compress_body,
    decode_infer_request,
    decompress_body,
    encode_infer_response,
)
from client_tpu.utils import InferenceServerException

HEADER_LEN = "Inference-Header-Content-Length"

Reply = Tuple[int, Dict[str, str], bytes]


def _json_reply(obj, status: int = 200,
                extra_headers: Optional[Dict[str, str]] = None) -> Reply:
    headers = {"Content-Type": "application/json"}
    if extra_headers:
        headers.update(extra_headers)
    return (status, headers, json.dumps(obj).encode())


def _int64_lists_to_ints(obj):
    """proto3 JSON stringifies int64 ("shape": ["16"]); the KServe
    REST spec wants integers. Fix shape/dims lists recursively."""
    if isinstance(obj, dict):
        return {
            key: ([int(d) for d in value]
                  if key in ("shape", "dims") and isinstance(value, list)
                  and all(isinstance(d, str) and d.lstrip("-").isdigit()
                          for d in value)
                  else _int64_lists_to_ints(value))
            for key, value in obj.items()
        }
    if isinstance(obj, list):
        return [_int64_lists_to_ints(v) for v in obj]
    return obj


def _pb_reply(message) -> Reply:
    return _json_reply(_int64_lists_to_ints(
        json_format.MessageToDict(message, preserving_proto_field_name=True)))


def _error_reply(error: InferenceServerException) -> Reply:
    # Retry-After on shed (503) and quota (429) replies: parity with
    # the aiohttp front-end — mapping + rounding policy in status_map.
    status = status_map.http_status(error.status())
    return _json_reply({"error": error.message()}, status,
                       status_map.retry_after_headers(status, error))


def _pick_encoding(accept_encoding: str) -> Optional[str]:
    for token in accept_encoding.split(","):
        parts = token.strip().lower().split(";")
        coding = parts[0].strip()
        if coding not in ("gzip", "deflate"):
            continue
        refused = any(
            p.strip().replace(" ", "") in ("q=0", "q=0.0", "q=0.00",
                                           "q=0.000")
            for p in parts[1:]
        )
        if not refused:
            return coding
    return None


_ROUTES = []  # (method, compiled pattern, handler(core, m, headers, body))


def _route(method: str, pattern: str):
    compiled = re.compile("^" + pattern + "$")

    def register(fn):
        _ROUTES.append((method, compiled, fn))
        return fn

    return register


_MODEL = r"/v2/models/(?P<model>[^/]+)(?:/versions/(?P<version>[^/]+))?"


@_route("GET", r"/v2/health/live")
def _live(core, m, headers, body):
    return (200 if core.server_live() else 400), {}, b""


@_route("GET", r"/v2/health/ready")
def _ready(core, m, headers, body):
    return (200 if core.server_ready() else 400), {}, b""


@_route("GET", _MODEL + r"/ready")
def _model_ready(core, m, headers, body):
    name = m.group("model")
    ready = core.model_ready(name, m.group("version") or "")
    # Parity with the aiohttp front-end: instance-group models expose
    # partial-degradation metadata on the ready probe.
    extra = {}
    health = core.replica_health(name)
    if health is not None:
        extra["x-replica-healthy"] = str(health[0])
        extra["x-replica-total"] = str(health[1])
    return (200 if ready else 400), extra, b""


@_route("GET", r"/metrics")
def _metrics(core, m, headers, body):
    # Same content negotiation as the aiohttp front-end: exemplars +
    # '# EOF' only for scrapers that negotiate OpenMetrics.
    openmetrics = "application/openmetrics-text" in \
        headers.get("accept", "")
    text = core.metrics_text(openmetrics)
    if openmetrics:
        return 200, {"Content-Type": "application/openmetrics-text; "
                                     "version=1.0.0; charset=utf-8"}, \
            text.encode()
    return 200, {"Content-Type": "text/plain; version=0.0.4"}, text.encode()


def _debug_query(m, headers) -> dict:
    """Parsed query for the debug routes. Direct http_call callers
    pass the raw request target (query included) and it matches off
    the path; the native HTTP/1.1 front-end strips the query before
    routing and forwards it as the synthetic ``x-request-query``
    header instead (http1_server.cc) — check both."""
    from urllib.parse import parse_qs, urlsplit

    query_string = urlsplit(m.string).query \
        or headers.get("x-request-query", "")
    return parse_qs(query_string)


def _debug_query_model(m, headers) -> str:
    return (_debug_query(m, headers).get("model") or [""])[0]


@_route("GET", r"/v2/debug(?:\?.*)?")
def _debug(core, m, headers, body):
    # Live introspection, aiohttp-front-end parity
    # (docs/flight_recorder.md).
    return _json_reply(core.debug_snapshot(_debug_query_model(m, headers)))


@_route("GET", r"/v2/debug/flight(?:\?.*)?")
def _debug_flight(core, m, headers, body):
    return _json_reply(core.debug_flight(_debug_query_model(m, headers)))


@_route("GET", r"/v2/debug/profile(?:\?.*)?")
def _debug_profile(core, m, headers, body):
    # On-demand bounded profiler capture, aiohttp-front-end parity
    # (docs/device_observability.md). The embedded dispatcher is
    # synchronous by design — the caller's worker thread blocks for
    # the (clamped) capture window.
    query = _debug_query(m, headers)
    try:
        duration_ms = int((query.get("duration_ms") or ["500"])[0])
    except ValueError:
        duration_ms = 500
    return _json_reply(core.debug_profile(
        duration_ms, (query.get("model") or [""])[0]))


@_route("GET", r"/v2")
def _server_metadata(core, m, headers, body):
    return _pb_reply(core.server_metadata())


@_route("GET", _MODEL + r"/config")
def _model_config(core, m, headers, body):
    response = core.model_config(m.group("model"), m.group("version") or "")
    return _pb_reply(response.config)


@_route("GET", _MODEL + r"/stats")
def _model_stats(core, m, headers, body):
    return _pb_reply(core.model_statistics(
        m.group("model"), m.group("version") or ""))


@_route("GET", r"/v2/models/stats")
def _all_stats(core, m, headers, body):
    return _pb_reply(core.model_statistics("", ""))


@_route("GET", _MODEL)
def _model_metadata(core, m, headers, body):
    return _pb_reply(core.model_metadata(
        m.group("model"), m.group("version") or ""))


@_route("POST", r"/v2/repository/index")
def _repo_index(core, m, headers, body):
    payload = json.loads(body) if body else {}
    index = core.repository_index(bool(payload.get("ready", False)))
    return _json_reply([
        {"name": entry.name, "version": entry.version,
         "state": entry.state, "reason": entry.reason}
        for entry in index.models
    ])


@_route("POST", r"/v2/repository/models/(?P<model>[^/]+)/load")
def _repo_load(core, m, headers, body):
    core.load_model(m.group("model"))
    return 200, {}, b""


@_route("POST", r"/v2/repository/models/(?P<model>[^/]+)/unload")
def _repo_unload(core, m, headers, body):
    core.unload_model(m.group("model"))
    return 200, {}, b""


@_route("GET", r"/v2/systemsharedmemory(?:/region/(?P<name>[^/]+))?/status")
def _sys_shm_status(core, m, headers, body):
    status = core.system_shm_status(m.group("name") or "")
    return _json_reply([
        {"name": region.name, "key": region.key,
         "offset": region.offset, "byte_size": region.byte_size}
        for region in status.regions.values()
    ])


@_route("POST", r"/v2/systemsharedmemory/region/(?P<name>[^/]+)/register")
def _sys_shm_register(core, m, headers, body):
    payload = json.loads(body)
    core.register_system_shm(
        m.group("name"), payload["key"], int(payload.get("offset", 0)),
        int(payload["byte_size"]))
    return 200, {}, b""


@_route("POST",
        r"/v2/systemsharedmemory(?:/region/(?P<name>[^/]+))?/unregister")
def _sys_shm_unregister(core, m, headers, body):
    core.unregister_system_shm(m.group("name") or "")
    return 200, {}, b""


@_route("GET", r"/v2/tpusharedmemory(?:/region/(?P<name>[^/]+))?/status")
def _tpu_shm_status(core, m, headers, body):
    status = core.tpu_shm_status(m.group("name") or "")
    return _json_reply([
        {"name": region.name, "device_id": region.device_id,
         "byte_size": region.byte_size}
        for region in status.regions.values()
    ])


@_route("POST", r"/v2/tpusharedmemory/region/(?P<name>[^/]+)/register")
def _tpu_shm_register(core, m, headers, body):
    import base64

    payload = json.loads(body)
    raw = payload.get("raw_handle", {}).get("b64", "")
    core.register_tpu_shm(
        m.group("name"), base64.b64decode(raw),
        int(payload.get("device_id", 0)), int(payload["byte_size"]))
    return 200, {}, b""


@_route("POST",
        r"/v2/tpusharedmemory(?:/region/(?P<name>[^/]+))?/unregister")
def _tpu_shm_unregister(core, m, headers, body):
    core.unregister_tpu_shm(m.group("name") or "")
    return 200, {}, b""


@_route("GET", r"/v2(?:/models/(?P<model>[^/]+))?/trace/setting")
def _get_trace(core, m, headers, body):
    settings = core.trace_setting(m.group("model") or "", {})
    return _json_reply(
        {k: v if len(v) != 1 else v[0] for k, v in settings.items()})


@_route("POST", r"/v2(?:/models/(?P<model>[^/]+))?/trace/setting")
def _post_trace(core, m, headers, body):
    updates = {
        k: (v if isinstance(v, list) else [v]) if v is not None else []
        for k, v in json.loads(body).items()
    }
    settings = core.trace_setting(m.group("model") or "", updates)
    return _json_reply(
        {k: v if len(v) != 1 else v[0] for k, v in settings.items()})


@_route("GET", r"/v2/logging")
def _get_logging(core, m, headers, body):
    return _json_reply(core.log_settings({}))


@_route("POST", r"/v2/logging")
def _post_logging(core, m, headers, body):
    return _json_reply(core.log_settings(json.loads(body)))


def _apply_tenant_header(headers, infer_request) -> None:
    """x-tenant-id -> `tenant` parameter (aiohttp front-end parity);
    an in-body parameter wins. Header names are lower-cased by the
    caller (http_call contract)."""
    tenant_header = headers.get("x-tenant-id")
    if tenant_header and "tenant" not in infer_request.parameters:
        infer_request.parameters["tenant"].string_param = tenant_header


@_route("POST", r"/v2/cancel/(?P<id>[^/]+)")
def _cancel_by_id(core, m, headers, body):
    """Explicit wire cancellation by request id (parity with the
    aiohttp front-end's route). The native transport also calls
    ``embed.http_cancel`` with this id directly when it sees the
    client socket hit EOF mid-request."""
    found = core.cancel_request(m.group("id"))
    return _json_reply({"cancelled": bool(found)},
                       200 if found else 404)


@_route("POST", _MODEL + r"/generate")
def _generate(core, m, headers, body):
    """Non-streaming generate extension (JSON in, JSON out); the SSE
    generate_stream variant stays on the aiohttp front-end."""
    from client_tpu.protocol.http_wire import (
        build_generate_request,
        generate_response_json,
    )

    body = decompress_body(body, headers.get("content-encoding"))
    model = core.repository.get(m.group("model"))
    infer_request = build_generate_request(
        model.inputs, m.group("model"), m.group("version") or "", body)
    # Same correlation/propagation hygiene as the /infer route below.
    from client_tpu.server.core import mint_request_id

    mint_request_id(infer_request)
    _apply_tenant_header(headers, infer_request)
    token = (core.cancel.mint(infer_request.id)
             if core.cancel.enabled else None)
    return _json_reply(generate_response_json(core.infer(
        infer_request, trace_context=headers.get("traceparent"),
        cancel=token)))


@_route("POST", _MODEL + r"/infer")
def _infer(core, m, headers, body):
    body = decompress_body(body, headers.get("content-encoding"))
    header_length = headers.get(HEADER_LEN.lower())
    infer_request = decode_infer_request(
        body, m.group("model"), m.group("version") or "",
        int(header_length) if header_length else None)
    from client_tpu.server.core import mint_request_id

    mint_request_id(infer_request)
    _apply_tenant_header(headers, infer_request)
    # Tracked token: the native transport watches the client socket
    # while this (synchronous) handler runs and calls
    # ``embed.http_cancel(request_id)`` on EOF — the id lookup below
    # is what makes a mid-flight embed disconnect land.
    token = (core.cancel.mint(infer_request.id)
             if core.cancel.enabled else None)
    # header names are lower-cased by the caller (http_call contract)
    response = core.infer(infer_request,
                          trace_context=headers.get("traceparent"),
                          cancel=token)
    binary_prefs = {}
    default_binary = False
    for tensor in infer_request.outputs:
        if "binary_data" in tensor.parameters:
            binary_prefs[tensor.name] = \
                tensor.parameters["binary_data"].bool_param
    if "binary_data_output" in infer_request.parameters:
        default_binary = \
            infer_request.parameters["binary_data_output"].bool_param
    payload, json_len = encode_infer_response(
        response, binary_prefs, default_binary)
    reply_headers = {"Content-Type": "application/octet-stream"
                     if json_len is not None else "application/json"}
    if json_len is not None:
        reply_headers[HEADER_LEN] = str(json_len)
    algorithm = _pick_encoding(headers.get("accept-encoding", ""))
    if algorithm:
        payload = compress_body(payload, algorithm)
        reply_headers["Content-Encoding"] = algorithm
    return 200, reply_headers, payload


def http_call(core, method: str, path: str, headers: Dict[str, str],
              body: bytes) -> Reply:
    """Dispatches one REST call; header names must be lower-cased by
    the caller. Unknown paths return 404, servicer errors map to the
    KServe error-JSON convention."""
    for route_method, pattern, handler in _ROUTES:
        if route_method != method:
            continue
        m = pattern.match(path)
        if m is None:
            continue
        try:
            return handler(core, m, headers, body)
        except InferenceServerException as e:
            return _error_reply(e)
        except Exception as e:  # noqa: BLE001 — malformed body etc.
            return _json_reply({"error": str(e)}, 400)
    return _json_reply({"error": "unknown route %s %s" % (method, path)},
                       404)
