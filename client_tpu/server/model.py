"""Served-model abstraction for the JAX/TPU inference server.

A ServedModel declares its I/O signature (KServe-v2 tensor metadata +
our ModelConfig) and implements ``infer`` — typically a ``jax.jit``-ed
function over device arrays. Decoupled models (token streaming)
implement ``infer_stream`` yielding zero-or-many responses.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol import model_config_pb2 as mc
from client_tpu.utils import InferenceServerException

_WIRE_TO_CONFIG_DTYPE = {
    "BOOL": mc.TYPE_BOOL, "UINT8": mc.TYPE_UINT8, "UINT16": mc.TYPE_UINT16,
    "UINT32": mc.TYPE_UINT32, "UINT64": mc.TYPE_UINT64, "INT8": mc.TYPE_INT8,
    "INT16": mc.TYPE_INT16, "INT32": mc.TYPE_INT32, "INT64": mc.TYPE_INT64,
    "FP16": mc.TYPE_FP16, "FP32": mc.TYPE_FP32, "FP64": mc.TYPE_FP64,
    "BYTES": mc.TYPE_BYTES, "BF16": mc.TYPE_BF16,
}
CONFIG_TO_WIRE_DTYPE = {v: k for k, v in _WIRE_TO_CONFIG_DTYPE.items()}


class TensorSpec:
    """Declared name/datatype/shape of one model input or output; -1
    dims are variable."""

    def __init__(self, name: str, datatype: str, shape: Sequence[int],
                 optional: bool = False):
        self.name = name
        self.datatype = datatype
        self.shape = [int(d) for d in shape]
        self.optional = optional

    def compatible_with(self, shape: Sequence[int]) -> bool:
        if len(shape) != len(self.shape):
            return False
        return all(d == -1 or int(d) == int(s) for d, s in zip(self.shape, shape))


class ServedModel:
    """Base class for everything the server can serve."""

    name: str = "model"
    version: str = "1"
    platform: str = "jax"
    max_batch_size: int = 0
    decoupled: bool = False
    # Server-side dynamic batching (client_tpu.server.batcher): fuse
    # concurrent requests along the batch dim into one XLA call.
    dynamic_batching: bool = False
    preferred_batch_sizes: list = []
    max_queue_delay_us: int = 500
    # Adaptive gather-window bounds: the batcher sizes the queue delay
    # from the observed inter-arrival rate, clamped to
    # [delay_min_us, delay_max_us]. 0 = derive from max_queue_delay_us
    # (min = the configured delay, max = 16x it).
    delay_min_us: int = 0
    delay_max_us: int = 0
    # Compute/fetch pipeline: max fused batches in flight at once
    # (0 = batcher default) and the device->host fetch pool size
    # (0 = sized from pipeline depth).
    pipeline_depth: int = 0
    fetch_pool_workers: int = 0
    # Output-fetch subsystem (client_tpu.server.fetch,
    # docs/zero_copy_fetch.md). overlapped_fetch=False opts this model
    # out of overlapped/chunked device->host output copies — back to
    # the serial blocking np.asarray per output (the bench A/B
    # baseline arm). fetch_chunk_bytes tunes the chunked-parallel
    # split threshold (0 = fetch.DEFAULT_CHUNK_BYTES); outputs at or
    # above 2x it land as concurrent per-slice copies.
    overlapped_fetch: bool = True
    fetch_chunk_bytes: int = 0
    # Queue policy (Triton ModelQueuePolicy semantics). max_queue_size
    # bounds pending requests in the dynamic batcher (0 = unbounded;
    # overflow rejected UNAVAILABLE at admission).
    # default_queue_policy_timeout_us starts each request's queue
    # deadline (0 = none); the per-request `timeout` parameter
    # overrides it when allow_timeout_override is set. timeout_action:
    # "REJECT" expires deadline-passed requests before dispatch
    # (DEADLINE_EXCEEDED); "DELAY" keeps them queued (advisory).
    max_queue_size: int = 0
    default_queue_policy_timeout_us: int = 0
    allow_timeout_override: bool = True
    timeout_action: str = "REJECT"
    # Multi-tenant QoS (client_tpu.server.qos + batcher priority
    # queues). priority_levels declares classes 1..N (1 highest;
    # requests pick theirs via the `priority` parameter — accepted
    # range 0..N, 0 = default_priority_level, out-of-range rejected
    # INVALID_ARGUMENT). default_priority_level 0 means the middle
    # level. priority_queue_policies maps a level to optional
    # {"max_queue_size", "default_timeout_us"} overrides (Triton's
    # per-priority ModelQueuePolicy). shed_watermark is the queue-
    # depth fraction of max_queue_size past which lowest-class
    # arrivals are shed (0 = displacement-only shedding).
    priority_levels: int = 0
    default_priority_level: int = 0
    priority_queue_policies: dict = {}
    shed_watermark: float = 0.0
    # Sequence batching (client_tpu.server.sequence): correlated
    # request streams are scheduled onto per-sequence slots. strategy
    # "direct" pins a slot per sequence and executes steps singly;
    # "oldest" dispatches steps through the dynamic batcher so
    # concurrent sequences' steps fuse into one execution.
    # max_candidate_sequences bounds live sequences (0 = scheduler
    # default); max_sequence_idle_us reclaims idle slots (0 = never).
    # sequence_controls: [{"name", "kind", "datatype"}] tensors the
    # scheduler injects per step (kinds CONTROL_SEQUENCE_START / _END /
    # _READY / _CORRID). sequence_states: [{"input_name",
    # "output_name", "datatype", "dims"}] implicit state carried
    # between steps, device-resident on TPU.
    # sequence_preferred_batch_sizes hints the oldest strategy's fused
    # step sizes (falls back to preferred_batch_sizes).
    # Response cache (client_tpu.server.cache): opt this model into
    # the server's content-addressed response cache — identical
    # requests are served the cached encoded response (bypassing
    # queue/batcher/execution) and concurrent identical misses
    # coalesce onto one execution (single-flight). The byte budget is
    # a SERVER-level knob (cache_size); decoupled models and sequence
    # requests always bypass.
    response_cache: bool = False
    # Replica serving (client_tpu.server.replicas): instance_group
    # declares N per-device replicas of this model behind an
    # in-process health-routed router — each replica its own
    # executable on its own serialized device queue and its own fault
    # domain (watchdog ejection, per-replica circuit breaker, bounded
    # once re-dispatch, supervisor self-healing). 0 (default) keeps
    # the legacy direct path; 1 engages the layer with a single fault
    # domain. instance_group_kind is KIND_AUTO/KIND_CPU/KIND_TPU
    # rendered in ModelConfig.instance_group.
    # replica_watchdog_us bounds one execution (0 = 5s default);
    # replica_failure_threshold consecutive failures eject a replica;
    # replica_recovery_s paces the breaker reset and the supervisor's
    # re-initialize + canary probe.
    instance_group_count: int = 0
    instance_group_kind: str = "auto"
    replica_watchdog_us: int = 0
    replica_failure_threshold: int = 0
    replica_recovery_s: float = 0.0
    # Mesh-slice serving (client_tpu.server.mesh, rendered in the
    # instance_group `shard_mesh` block): a shard-mesh spec — ordered
    # axis sizes, e.g. {"tp": 4} or "sp=2,tp=2" — turns each replica
    # into a tensor-parallel SLICE of slice_width (= axis product)
    # devices: the factory is invoked with mesh=<slice mesh> to build
    # one sharded executable per slice, weights are leased per member
    # device, and the fault domain is the whole device set. Empty
    # (default) keeps classic one-device replicas. Requires
    # instance_group_count >= 1 (the replica axis composes on top).
    shard_mesh: dict = {}
    # Autoscaling (client_tpu.server.autoscale, rendered in the
    # instance_group `autoscale` block): the per-model feedback
    # controller resizes the ReplicaSet between min/max replicas.
    # autoscale_max_replicas 0 (default) disables the controller;
    # min_replicas 0 with a nonzero idle window allows scale-to-zero
    # (the model unloads entirely when idle and cold-starts on the
    # next arrival with an honest Retry-After). queue_high is the
    # pending-per-healthy-replica depth that triggers growth;
    # duty_high/duty_low are device duty-cycle bands; the cooldowns
    # are the hysteresis floor between consecutive resizes in each
    # direction. interval_s paces the control loop (0 = 1s default).
    autoscale_min_replicas: int = 0
    autoscale_max_replicas: int = 0
    autoscale_interval_s: float = 0.0
    autoscale_queue_high: float = 0.0
    autoscale_duty_high: float = 0.0
    autoscale_duty_low: float = 0.0
    autoscale_up_cooldown_s: float = 0.0
    autoscale_down_cooldown_s: float = 0.0
    autoscale_idle_s: float = 0.0
    # Service-level objectives (client_tpu.server.slo, rendered in the
    # ModelConfig `slo` block): 0 = objective not declared. The SLO
    # engine computes error-budget burn rate per objective over
    # fast/slow sliding windows and exposes the tpu_slo_* families +
    # SloStatistics — the signal the autoscaling/admission controller
    # consumes. slo_availability is a fraction (e.g. 0.999); errors,
    # rejects, deadline expiries, and sheds all spend its budget.
    slo_p99_latency_us: int = 0
    slo_ttft_p99_us: int = 0
    slo_availability: float = 0.0
    # Flight recorder (client_tpu.server.flight): absolute slow-keep
    # threshold for this model's retroactive trace retention. 0 =
    # derive the threshold live from the model's request-duration
    # histogram (estimated p99).
    flight_slow_us: int = 0
    # Weight paging (client_tpu.server.hbm): pageable_weights opts
    # this model's weights into the allocator's page-out path — cold
    # models move their weights to host (scale-to-zero, eviction
    # under HBM pressure) and restore them chunked-parallel on the
    # next arrival. A pageable model must implement weight_state()
    # (return the live weights pytree) and set_weight_state() (accept
    # a replacement pytree, device or host); models that keep the
    # default (None state) are treated as non-pageable regardless of
    # the flag.
    pageable_weights: bool = False
    sequence_batching: bool = False
    sequence_strategy: str = "direct"
    max_candidate_sequences: int = 0
    max_sequence_idle_us: int = 0
    sequence_controls: list = []
    sequence_states: list = []
    sequence_preferred_batch_sizes: list = []

    def __init__(self):
        self.inputs: List[TensorSpec] = []
        self.outputs: List[TensorSpec] = []

    # -- to be implemented by concrete models ---------------------------

    def infer(
        self, inputs: Dict[str, np.ndarray], parameters: Optional[dict] = None
    ) -> Dict[str, np.ndarray]:
        raise InferenceServerException(
            "model '%s' does not implement one-shot inference" % self.name
        )

    def infer_stream(
        self, inputs: Dict[str, np.ndarray], parameters: Optional[dict] = None
    ) -> Iterator[Dict[str, np.ndarray]]:
        raise InferenceServerException(
            "model '%s' is not decoupled" % self.name
        )

    def warmup(self) -> None:
        """Trigger jit compilation ahead of traffic (optional)."""

    def unload(self) -> None:
        """Release device resources (optional)."""

    def weight_state(self):
        """The live weights pytree for paging (docs/hbm.md). None
        (the default) marks the model non-pageable even when
        ``pageable_weights`` is set."""
        return None

    def set_weight_state(self, state) -> None:
        """Replace the weights pytree (host copies at page-out,
        device copies at restore). Only called when weight_state()
        returned a pytree."""

    def flops_estimate(self, batch: int, seq: int = 0):
        """Analytic FLOPs for ONE forward execution at this batch size
        (``seq`` for sequence models) — the MFU numerator the bench
        divides by measured device time.  None = not modeled."""
        return None

    # -- protocol views --------------------------------------------------

    def metadata_pb(self) -> pb.ModelMetadataResponse:
        meta = pb.ModelMetadataResponse(
            name=self.name, versions=[self.version], platform=self.platform
        )
        batch_dim = [-1] if self.max_batch_size > 0 else []
        for spec in self.inputs:
            meta.inputs.add(
                name=spec.name, datatype=spec.datatype,
                shape=batch_dim + spec.shape,
            )
        for spec in self.outputs:
            meta.outputs.add(
                name=spec.name, datatype=spec.datatype,
                shape=batch_dim + spec.shape,
            )
        return meta

    def config_pb(self) -> mc.ModelConfig:
        config = mc.ModelConfig(
            name=self.name,
            platform=self.platform,
            backend="jax",
            max_batch_size=self.max_batch_size,
            versions=[self.version],
        )
        for spec in self.inputs:
            config.input.add(
                name=spec.name,
                data_type=_WIRE_TO_CONFIG_DTYPE[spec.datatype],
                dims=spec.shape,
                optional=spec.optional,
            )
        for spec in self.outputs:
            config.output.add(
                name=spec.name,
                data_type=_WIRE_TO_CONFIG_DTYPE[spec.datatype],
                dims=spec.shape,
            )
        config.model_transaction_policy.decoupled = self.decoupled
        if self.response_cache:
            config.response_cache.enable = True
        if (self.slo_p99_latency_us or self.slo_ttft_p99_us
                or self.slo_availability):
            config.slo.p99_latency_us = self.slo_p99_latency_us
            config.slo.ttft_p99_us = self.slo_ttft_p99_us
            config.slo.availability = self.slo_availability
        if self.instance_group_count > 0:
            kind = {
                "cpu": mc.ModelInstanceConfig.KIND_CPU,
                "tpu": mc.ModelInstanceConfig.KIND_TPU,
            }.get(str(self.instance_group_kind).lower(),
                  mc.ModelInstanceConfig.KIND_AUTO)
            group = config.instance_group.add(
                name="%s_0" % self.name, kind=kind,
                count=self.instance_group_count)
            if self.autoscale_max_replicas > 0:
                auto = group.autoscale
                auto.min_replicas = self.autoscale_min_replicas
                auto.max_replicas = self.autoscale_max_replicas
                auto.interval_s = self.autoscale_interval_s
                auto.queue_high = self.autoscale_queue_high
                auto.duty_high = self.autoscale_duty_high
                auto.duty_low = self.autoscale_duty_low
                auto.up_cooldown_s = self.autoscale_up_cooldown_s
                auto.down_cooldown_s = self.autoscale_down_cooldown_s
                auto.idle_s = self.autoscale_idle_s
            if self.shard_mesh:
                from client_tpu.server import mesh as mesh_mod

                sm = group.shard_mesh
                for axis, size in mesh_mod.parse_shard_mesh(
                        self.shard_mesh):
                    sm.axis_names.append(axis)
                    sm.axis_sizes.append(size)
        if self.dynamic_batching:
            config.dynamic_batching.preferred_batch_size.extend(
                self.preferred_batch_sizes)
            config.dynamic_batching.max_queue_delay_microseconds = (
                self.max_queue_delay_us)
            config.dynamic_batching.default_queue_policy_timeout_us = (
                self.default_queue_policy_timeout_us)
            config.dynamic_batching.max_queue_size = self.max_queue_size
            config.dynamic_batching.allow_timeout_override = (
                self.allow_timeout_override)
            config.dynamic_batching.timeout_action = self.timeout_action
            # Accepted `priority` parameter range once rendered:
            # 0..priority_levels (0 = default_priority_level; 1 is the
            # highest class). Out-of-range is INVALID_ARGUMENT.
            config.dynamic_batching.priority_levels = self.priority_levels
            config.dynamic_batching.default_priority_level = (
                self.default_priority_level)
            config.dynamic_batching.shed_watermark = self.shed_watermark
            for level in sorted(self.priority_queue_policies):
                policy = self.priority_queue_policies[level]
                config.dynamic_batching.priority_queue_policy.add(
                    priority_level=int(level),
                    max_queue_size=int(policy.get("max_queue_size", 0)),
                    default_timeout_us=int(
                        policy.get("default_timeout_us", 0)))
        if self.sequence_batching:
            from client_tpu.server.sequence import (
                DEFAULT_CANDIDATE_SEQUENCES,
            )

            sb = config.sequence_batching
            sb.SetInParent()
            sb.strategy = self.sequence_strategy or "direct"
            sb.max_candidate_sequences = (
                self.max_candidate_sequences or DEFAULT_CANDIDATE_SEQUENCES)
            sb.max_sequence_idle_microseconds = self.max_sequence_idle_us
            for entry in self.sequence_controls:
                sb.control_input.add(
                    name=entry["name"], kind=entry["kind"],
                    data_type=_WIRE_TO_CONFIG_DTYPE[
                        entry.get("datatype", "INT32")])
            for entry in self.sequence_states:
                state = sb.state.add(
                    input_name=entry["input_name"],
                    output_name=entry["output_name"],
                    data_type=_WIRE_TO_CONFIG_DTYPE[
                        entry.get("datatype", "FP32")])
                state.dims.extend(
                    int(d) for d in entry.get("dims", (1,)))
            sb.preferred_batch_size.extend(
                self.sequence_preferred_batch_sizes
                or self.preferred_batch_sizes)
        self._extend_config(config)
        return config

    def _extend_config(self, config: mc.ModelConfig) -> None:
        """Hook for subclasses (dynamic batching, ensemble, mesh...)."""

    def find_input(self, name: str) -> Optional[TensorSpec]:
        for spec in self.inputs:
            if spec.name == name:
                return spec
        return None

    def find_output(self, name: str) -> Optional[TensorSpec]:
        for spec in self.outputs:
            if spec.name == name:
                return spec
        return None
