"""Per-model SLO targets + multi-window error-budget burn rate.

ROADMAP item 4 (closed-loop autoscaling, SLO-aware admission) needs a
signal that did not exist: no model declared an SLO and nothing
computed burn rate against one. This module is that signal. A model
declares its objectives in the ModelConfig ``slo`` block
(:class:`SloTarget`):

* ``p99_latency_us`` — 99% of served requests complete within this;
* ``ttft_p99_us`` — 99% of streams produce their first response
  within this (token streams);
* ``availability`` — fraction of admitted requests that must succeed
  (e.g. ``0.999``; errors, queue rejects, deadline expiries, and
  sheds all spend the budget).

The engine computes **error-budget burn rate** over two sliding
windows (the Google SRE workbook's multi-window methodology: a fast
window catches a cliff in minutes, a slow window catches a steady
leak) from telemetry the server already records — the always-on
``tpu_request_duration_us`` / ``tpu_stream_first_response_us``
histograms (PR 10) and the per-model success/failure counters. Burn
rate 1.0 means the budget is being spent exactly as fast as the SLO
allows; >1 means the budget will exhaust before the window does.

Derivation, per objective, over a window ``[t-w, t]``:

* latency/TTFT: ``bad_fraction = fraction of observations above the
  target`` (estimated from cumulative bucket deltas, interpolating
  inside the bucket containing the target);
  ``burn = bad_fraction / (1 - 0.99)``.
* availability: ``bad_fraction = failed / (failed + succeeded)``;
  ``burn = bad_fraction / (1 - availability)``.

The model's burn rate is the max over its declared objectives. The
``tpu_slo_healthy`` verdict applies the multi-window rule: unhealthy
only when BOTH windows burn above 1 — a fast-window spike alone is
noise, a slow-window overrun with a calm fast window is already
recovering. A transition to unhealthy stamps the model's flight-ring
traces (:meth:`FlightRecorder.mark_incident`) so the forensic layer
names the burn they contributed to.

Sampling is lazy: :meth:`evaluate` appends a cumulative snapshot at
most once per ``min_sample_interval_s`` and computes burns between the
newest snapshot and the newest one at least a window old (the window
"ramps" from whatever history exists — a fresh server reports burn
over its lifetime until the window fills). No background thread; an
idle server pays nothing.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Dict, List, Optional

DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_SAMPLE_INTERVAL_S = 5.0

# The quantile both latency objectives target (p99): the allowed bad
# fraction their burn rates normalize by.
LATENCY_QUANTILE = 0.99


class SloTarget:
    """One model's declared objectives (0 = objective not declared)."""

    __slots__ = ("p99_latency_us", "ttft_p99_us", "availability")

    def __init__(self, p99_latency_us: int = 0, ttft_p99_us: int = 0,
                 availability: float = 0.0):
        self.p99_latency_us = int(p99_latency_us or 0)
        self.ttft_p99_us = int(ttft_p99_us or 0)
        self.availability = float(availability or 0.0)

    def declared(self) -> bool:
        return bool(self.p99_latency_us or self.ttft_p99_us
                    or self.availability)

    @classmethod
    def of(cls, model) -> "SloTarget":
        return cls(getattr(model, "slo_p99_latency_us", 0),
                   getattr(model, "slo_ttft_p99_us", 0),
                   getattr(model, "slo_availability", 0.0))


def wants_slo(model) -> bool:
    return SloTarget.of(model).declared()


def count_at_or_below(buckets, threshold_us: float) -> float:
    """Estimated observations at or below ``threshold_us`` from
    CUMULATIVE ``(le, count)`` pairs (telemetry snapshot order),
    interpolating linearly inside the bucket containing the
    threshold — the inverse of ``estimate_quantile``."""
    pairs = sorted(buckets, key=lambda pair: pair[0])
    if not pairs:
        return 0.0
    bounds = [b for b, _ in pairs]
    idx = bisect_left(bounds, threshold_us)
    if idx >= len(pairs):
        return float(pairs[-1][1])
    bound, cum = pairs[idx]
    prev_bound = pairs[idx - 1][0] if idx > 0 else 0.0
    prev_cum = pairs[idx - 1][1] if idx > 0 else 0.0
    if bound == float("inf") or bound <= prev_bound:
        return float(prev_cum)
    fraction = (threshold_us - prev_bound) / (bound - prev_bound)
    fraction = min(max(fraction, 0.0), 1.0)
    return prev_cum + (cum - prev_cum) * fraction


class SloSample:
    """One cumulative snapshot of the counters a burn computation
    differences. All fields are cumulative-since-start.
    ``latency_monitored`` / ``ttft_monitored`` flag whether the
    latency sources were actually recording when collected (telemetry
    can be disabled): a declared objective whose source is off must
    fail the verdict loudly, never report burn 0."""

    __slots__ = ("ts", "latency_total", "latency_good", "ttft_total",
                 "ttft_good", "ok_count", "bad_count",
                 "latency_monitored", "ttft_monitored")

    def __init__(self, ts: float, latency_total: float = 0.0,
                 latency_good: float = 0.0, ttft_total: float = 0.0,
                 ttft_good: float = 0.0, ok_count: float = 0.0,
                 bad_count: float = 0.0, latency_monitored: bool = True,
                 ttft_monitored: bool = True):
        self.ts = ts
        self.latency_total = latency_total
        self.latency_good = latency_good
        self.ttft_total = ttft_total
        self.ttft_good = ttft_good
        self.ok_count = ok_count
        self.bad_count = bad_count
        self.latency_monitored = latency_monitored
        self.ttft_monitored = ttft_monitored


class SloEngine:
    """Burn-rate computation over a ring of :class:`SloSample`s per
    model. ``targets_fn`` lists the (model_name, target, model)
    triples currently served; ``collect_fn(model_name, target)``
    returns a fresh cumulative :class:`SloSample` (the core wires both
    to its telemetry registry and stats); ``incident_hook(model,
    label)`` fires on a healthy->unhealthy transition."""

    def __init__(self, targets_fn: Callable[[], list],
                 collect_fn: Callable[[str, SloTarget], SloSample],
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 min_sample_interval_s: float = DEFAULT_SAMPLE_INTERVAL_S,
                 now_fn: Optional[Callable[[], float]] = None,
                 incident_hook: Optional[Callable[[str, str], None]]
                 = None):
        import time as _time

        self._targets_fn = targets_fn
        self._collect_fn = collect_fn
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.min_sample_interval_s = float(min_sample_interval_s)
        self._now = now_fn or _time.monotonic
        self._incident_hook = incident_hook
        # Implicit zero baseline: every cumulative counter was 0 when
        # the engine was created, so a model's first real sample can
        # difference against (t0, zeros) — without it, a run shorter
        # than one sample interval would always report burn 0.
        self._t0 = self._now()
        self._lock = threading.Lock()
        # model -> list of SloSample, oldest first, pruned past the
        # slow window (+ one sample of slack so the window boundary
        # always has a baseline).
        self._samples: Dict[str, List[SloSample]] = {}
        self._healthy: Dict[str, bool] = {}
        # (ts, verdicts) of the last evaluate() — see cached_verdicts.
        self._last_verdicts: tuple = (0.0, None)

    # -- sampling ----------------------------------------------------------

    def _store_sample(self, model_name: str, fresh: SloSample,
                      force: bool) -> List[SloSample]:
        """The ONE locked append path (shared by sample() and
        evaluate()): interval check, ts-ordering guard, and
        slow-window prune all happen atomically, so concurrent
        callers can neither double-append within one interval nor
        insert an older-ts sample after a newer one (the baseline
        scan assumes ts order). Returns a snapshot of the model's
        history including any just-stored sample."""
        with self._lock:
            samples = self._samples.get(model_name)
            if samples is None:
                # Implicit zero baseline at engine start: cumulative
                # counters were all 0 then, so the first real sample
                # has something honest to difference against.
                samples = self._samples[model_name] = [
                    SloSample(self._t0)]
            due = (force or len(samples) == 1
                   or fresh.ts - samples[-1].ts
                   >= self.min_sample_interval_s)
            if due and fresh.ts >= samples[-1].ts:
                samples.append(fresh)
                # Prune everything older than the slow window except
                # the newest such sample — the boundary baseline.
                horizon = fresh.ts - self.slow_window_s
                while len(samples) > 2 and samples[1].ts <= horizon:
                    samples.pop(0)
            return list(samples)

    def sample(self, force: bool = False) -> None:
        """Appends a cumulative snapshot per SLO-declaring model if the
        newest one is older than ``min_sample_interval_s`` (``force``
        skips the interval check — tests and window-boundary
        verification)."""
        now = self._now()
        try:
            targets = self._targets_fn()
        except Exception:  # noqa: BLE001 — observability never raises
            return
        for model_name, target, _model in targets:
            with self._lock:
                samples = self._samples.get(model_name)
                if samples and len(samples) > 1 and not force and \
                        now - samples[-1].ts < self.min_sample_interval_s:
                    continue  # cheap pre-check; _store_sample re-checks
            try:
                snapshot = self._collect_fn(model_name, target)
            except Exception:  # noqa: BLE001
                continue
            snapshot.ts = now
            self._store_sample(model_name, snapshot, force)

    @staticmethod
    def _burns(target: SloTarget, old: SloSample,
               new: SloSample) -> Dict[str, float]:
        """Per-objective burn rates between two cumulative samples."""
        out: Dict[str, float] = {}
        if target.p99_latency_us:
            total = max(new.latency_total - old.latency_total, 0.0)
            good = max(new.latency_good - old.latency_good, 0.0)
            if total > 0:
                bad_fraction = max(total - good, 0.0) / total
                out["p99_latency_us"] = bad_fraction \
                    / (1.0 - LATENCY_QUANTILE)
        if target.ttft_p99_us:
            total = max(new.ttft_total - old.ttft_total, 0.0)
            good = max(new.ttft_good - old.ttft_good, 0.0)
            if total > 0:
                bad_fraction = max(total - good, 0.0) / total
                out["ttft_p99_us"] = bad_fraction \
                    / (1.0 - LATENCY_QUANTILE)
        if target.availability:
            ok = max(new.ok_count - old.ok_count, 0.0)
            bad = max(new.bad_count - old.bad_count, 0.0)
            allowed = 1.0 - min(target.availability, 0.999999)
            if ok + bad > 0:
                out["availability"] = (bad / (ok + bad)) / allowed
        return out

    def _window_baseline(self, samples: List[SloSample],
                         window_s: float) -> Optional[SloSample]:
        """The newest sample at least ``window_s`` old, else the
        oldest sample (the ramping window), else None."""
        if len(samples) < 2:
            return None
        horizon = samples[-1].ts - window_s
        baseline = None
        for sample in samples[:-1]:
            if sample.ts <= horizon:
                baseline = sample
            else:
                break
        return baseline or samples[0]

    # -- evaluation --------------------------------------------------------

    def evaluate(self, force_sample: bool = False) -> Dict[str, dict]:
        """Samples (rate-limited) then computes the per-model verdict:
        ``{model: {"target": {...}, "burn": {"fast": x, "slow": y},
        "objectives": {objective: fast_burn}, "budget_remaining": b,
        "healthy": bool}}``. The "now" endpoint of every burn is a
        FRESH collect (never the last stored sample): a scrape mid-
        incident must report the incident, not a point up to
        ``min_sample_interval_s`` stale — the stored ring only
        provides the window baselines. The same collect doubles as
        the stored sample when the interval has elapsed (one
        collection per model per evaluation, not two)."""
        now = self._now()
        try:
            targets = {name: target
                       for name, target, _m in self._targets_fn()}
        except Exception:  # noqa: BLE001
            targets = {}
        out: Dict[str, dict] = {}
        transitions: List[str] = []
        for model_name, target in targets.items():
            try:
                fresh = self._collect_fn(model_name, target)
            except Exception:  # noqa: BLE001
                continue
            fresh.ts = now
            history = self._store_sample(model_name, fresh,
                                         force_sample)
            if history[-1] is not fresh:
                history = history + [fresh]
            burns = {"fast": 0.0, "slow": 0.0}
            objectives: Dict[str, float] = {}
            for window_name, window_s in (
                    ("fast", self.fast_window_s),
                    ("slow", self.slow_window_s)):
                baseline = self._window_baseline(history, window_s)
                if baseline is None:
                    continue
                per_objective = self._burns(target, baseline, fresh)
                if window_name == "fast":
                    objectives = per_objective
                if per_objective:
                    burns[window_name] = max(per_objective.values())
            # Multi-window verdict: unhealthy only when both windows
            # burn above 1 (fast alone = transient spike, slow alone =
            # an old overrun already recovering).
            healthy = not (burns["fast"] > 1.0 and burns["slow"] > 1.0)
            # A declared objective whose data source is off (telemetry
            # disabled) is UNMONITORABLE: burn 0 would be a silent
            # lie, so the verdict fails loudly instead — perf --slo
            # and the controller both see unhealthy.
            monitored = not (
                (target.p99_latency_us and not fresh.latency_monitored)
                or (target.ttft_p99_us and not fresh.ttft_monitored))
            if not monitored:
                healthy = False
            budget_remaining = max(0.0, 1.0 - burns["slow"])
            verdict = {
                "target": {
                    "p99_latency_us": target.p99_latency_us,
                    "ttft_p99_us": target.ttft_p99_us,
                    "availability": target.availability,
                },
                "burn": burns,
                "objectives": objectives,
                "budget_remaining": budget_remaining,
                "healthy": healthy,
                "monitored": monitored,
                "samples": len(history),
            }
            out[model_name] = verdict
            with self._lock:
                was_healthy = self._healthy.get(model_name, True)
                self._healthy[model_name] = healthy
            if was_healthy and not healthy:
                transitions.append(model_name)
        # Incident stamping OUTSIDE the lock (the hook serializes the
        # flight ring; holding our lock across it would couple the two
        # subsystems' lock orders for no reason).
        if self._incident_hook is not None:
            for model_name in transitions:
                burns = out[model_name]["burn"]
                try:
                    self._incident_hook(
                        model_name,
                        "slo_burn fast=%.2f slow=%.2f"
                        % (burns["fast"], burns["slow"]))
                except Exception:  # noqa: BLE001 — stamping is advisory
                    pass
        with self._lock:
            self._last_verdicts = (now, out)
        return out

    def cached_verdicts(self, max_age_s: float = 1.0) -> Dict[str, dict]:
        """The last ``evaluate()`` result when it is at most
        ``max_age_s`` old, else a fresh evaluation. The autoscale
        controller and the metrics scrape both want verdicts every
        tick; sharing one collect between near-simultaneous callers
        halves the per-model statistics walks without letting either
        consumer act on stale burn rates."""
        now = self._now()
        with self._lock:
            ts, cached = self._last_verdicts
            if cached is not None and (now - ts) <= max_age_s:
                return cached
        return self.evaluate()

    # -- exposition --------------------------------------------------------

    def render(self) -> List[str]:
        """Prometheus exposition lines for the tpu_slo_* families
        (empty when no model declares an SLO, so idle scrapes stay
        small)."""
        verdicts = self.evaluate()
        if not verdicts:
            return []
        lines: List[str] = []

        def family(name, help_text, rows, kind="gauge"):
            if not rows:
                return
            lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)

        target_rows: List[str] = []
        burn_rows: List[str] = []
        budget_rows: List[str] = []
        healthy_rows: List[str] = []
        for model_name in sorted(verdicts):
            verdict = verdicts[model_name]
            target = verdict["target"]
            for objective in ("p99_latency_us", "ttft_p99_us",
                              "availability"):
                value = target[objective]
                if value:
                    target_rows.append(
                        'tpu_slo_target{model="%s",objective="%s"} %s'
                        % (model_name, objective, repr(float(value))))
            for window in ("fast", "slow"):
                burn_rows.append(
                    'tpu_slo_burn_rate{model="%s",window="%s"} %.6f'
                    % (model_name, window, verdict["burn"][window]))
            budget_rows.append(
                'tpu_slo_budget_remaining{model="%s"} %.6f'
                % (model_name, verdict["budget_remaining"]))
            healthy_rows.append(
                'tpu_slo_healthy{model="%s"} %d'
                % (model_name, 1 if verdict["healthy"] else 0))
        family("tpu_slo_target",
               "Declared SLO objective value per model (latency "
               "targets in us, availability as a fraction)",
               target_rows)
        family("tpu_slo_burn_rate",
               "Error-budget burn rate per sliding window (1.0 = "
               "budget spent exactly as fast as the SLO allows; the "
               "max over the model's declared objectives)", burn_rows)
        family("tpu_slo_budget_remaining",
               "Fraction of the slow-window error budget left "
               "(1 - slow burn, clamped at 0)", budget_rows)
        family("tpu_slo_healthy",
               "Multi-window SLO verdict: 0 when BOTH windows burn "
               "above 1 (the signal the autoscaling/admission "
               "controller consumes)", healthy_rows)
        return lines
