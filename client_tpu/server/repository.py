"""Model repository: named models with explicit load/unload and an
index — the server-side counterpart of the client's model-control APIs
(RepositoryIndex / RepositoryModelLoad / RepositoryModelUnload)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from client_tpu import status_map
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server.model import ServedModel
from client_tpu.utils import InferenceServerException


class ModelRepository:
    # Bounded wait for in-flight requests at unload: long enough for
    # any sane inference, short enough that a wedged request cannot
    # hold a model's device memory hostage forever.
    DRAIN_TIMEOUT_S = 10.0

    def __init__(self):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._models: Dict[str, ServedModel] = {}
        self._factories: Dict[str, Callable[[], ServedModel]] = {}
        self._state: Dict[str, str] = {}
        self._reason: Dict[str, str] = {}
        self._inflight: Dict[str, int] = {}
        # Called with the model name after an unload's drain completes
        # (and before a reload can serve): the response cache hooks in
        # here so a reloaded instance never serves another instance's
        # cached bytes.
        self._unload_listeners: List[Callable[[str], None]] = []

    def add_unload_listener(self, listener: Callable[[str], None]) -> None:
        with self._lock:
            self._unload_listeners.append(listener)

    def add_factory(self, name: str, factory: Callable[[], ServedModel]) -> None:
        """Make ``name`` loadable on demand without instantiating it."""
        with self._lock:
            self._factories[name] = factory
            self._state.setdefault(name, "UNAVAILABLE")

    def add_model(self, model: ServedModel, warmup: bool = False) -> None:
        with self._lock:
            self._models[model.name] = model
            # reload-after-unload resurrects this exact instance (a
            # bare type() factory would lose constructor arguments)
            self._factories.setdefault(model.name, lambda model=model: model)
            self._state[model.name] = "READY"
            self._reason.pop(model.name, None)
        if warmup:
            model.warmup()

    def load(self, name: str) -> ServedModel:
        with self._lock:
            if name in self._models:
                self._state[name] = "READY"
                return self._models[name]
            factory = self._factories.get(name)
            if factory is None:
                raise InferenceServerException(
                    "unknown model '%s'" % name, status="NOT_FOUND"
                )
        try:
            model = factory()
        except Exception as e:
            with self._lock:
                self._state[name] = "UNAVAILABLE"
                self._reason[name] = str(e)
            raise InferenceServerException(
                "failed to load model '%s': %s" % (name, e), status="INTERNAL"
            )
        with self._lock:
            self._models[name] = model
            self._state[name] = "READY"
            self._reason.pop(name, None)
        return model

    # -- graceful unload --------------------------------------------------
    #
    # unload is a three-phase drain, NOT a pop-and-teardown: (1) flip
    # the state so new requests are shed with UNAVAILABLE (HTTP 503 +
    # Retry-After) while /..../ready goes false for load balancers,
    # (2) wait — bounded — for the per-model in-flight counter to hit
    # zero, (3) only then drop the instance and release its device
    # resources. Tearing down while a request holds the model's jitted
    # functions/device buffers is a use-after-free in spirit even when
    # Python keeps the objects alive.

    def begin_unload(self, name: str) -> None:
        """Phase 1: stop admitting requests for ``name``."""
        with self._lock:
            if name not in self._models and name not in self._factories:
                raise InferenceServerException(
                    "unknown model '%s'" % name, status="NOT_FOUND"
                )
            self._state[name] = "UNAVAILABLE"
            self._reason[name] = "unloading: draining in-flight requests"

    def finish_unload(self, name: str,
                      drain_timeout_s: Optional[float] = None) -> None:
        """Phases 2+3: bounded in-flight drain, then teardown."""
        timeout = self.DRAIN_TIMEOUT_S if drain_timeout_s is None \
            else drain_timeout_s
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight.get(name, 0) > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # wedged request: tear down anyway, loudly
                self._cv.wait(timeout=remaining)
            leaked = self._inflight.pop(name, 0)
            model = self._models.pop(name, None)
            self._reason[name] = "unloaded" if not leaked else (
                "unloaded with %d request(s) still in flight after "
                "%.1fs drain" % (leaked, timeout))
        try:
            if model is not None:
                model.unload()
        finally:
            # Listeners ALWAYS fire, even when the model's own
            # teardown raises: the response cache invalidates here,
            # and skipping it would let a reloaded instance serve the
            # crashed instance's cached bytes (tpulint:
            # resource-pairing found the unprotected ordering).
            for listener in list(self._unload_listeners):
                try:
                    listener(name)
                except Exception:  # noqa: BLE001 — teardown must not raise
                    pass

    def unload(self, name: str,
               drain_timeout_s: Optional[float] = None) -> None:
        # tpulint: disable=resource-pairing -- begin and finish are
        # adjacent: no statement between them can raise and strand the
        # drain state
        self.begin_unload(name)
        self.finish_unload(name, drain_timeout_s)

    # -- weight paging (client_tpu.server.hbm) ----------------------------
    #
    # Page-out is phases 1+2 of the unload drain WITHOUT phase 3: the
    # instance stays registered (its ledger rows move to the
    # paged-out side table, they don't vanish), admission sheds with
    # the same honest 503 + Retry-After, and mark_ready reverses it
    # after restore — no factory round-trip, no re-warmup.

    def drain(self, name: str, drain_timeout_s: Optional[float] = None,
              reason: str = "weights paged out to host") -> bool:
        """Bounded wait for ``name``'s in-flight counter to reach
        zero while keeping the instance (begin_unload must already
        have flipped admission off). False when requests were still
        in flight at the deadline — the caller must not move the
        weights out from under them."""
        timeout = self.DRAIN_TIMEOUT_S if drain_timeout_s is None \
            else drain_timeout_s
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight.get(name, 0) > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
            self._reason[name] = reason
        return True

    def mark_ready(self, name: str) -> None:
        """Re-admit a paged-out (or otherwise quiesced-but-loaded)
        model after its weights are device-resident again."""
        with self._lock:
            if name not in self._models:
                raise InferenceServerException(
                    "unknown model '%s'" % name, status="NOT_FOUND"
                )
            self._state[name] = "READY"
            self._reason.pop(name, None)

    # -- in-flight accounting ---------------------------------------------

    def acquire(self, name: str, version: str = "") -> ServedModel:
        """Admission for one inference: the READY check and the
        in-flight increment are one atomic step, so an unload that
        begins after admission waits for this request and an unload
        that began before it sheds this request — never both."""
        with self._lock:
            model = self._models.get(name)
            if model is None:
                raise InferenceServerException(
                    "request for unknown model: '%s' is not found" % name,
                    status="NOT_FOUND",
                )
            if self._state.get(name) != "READY":
                # Retry-After: an unloading model's drain is bounded by
                # DRAIN_TIMEOUT_S but typically finishes in well under
                # a fifth of it; a reload needs about the same. tpulint
                # (retry-after) keeps every shed path honest like this.
                raise status_map.retryable_error(
                    "model '%s' is unavailable: %s"
                    % (name, self._reason.get(name, "not ready")),
                    retry_after_s=self.DRAIN_TIMEOUT_S / 5.0,
                )
            if version and model.version != version:
                raise InferenceServerException(
                    "request for unknown model version: '%s' version %s"
                    % (name, version),
                    status="NOT_FOUND",
                )
            self._inflight[name] = self._inflight.get(name, 0) + 1
            return model

    def release(self, name: str) -> None:
        with self._cv:
            count = self._inflight.get(name, 0) - 1
            if count <= 0:
                self._inflight.pop(name, None)
                self._cv.notify_all()
            else:
                self._inflight[name] = count

    def inflight(self, name: str) -> int:
        with self._lock:
            return self._inflight.get(name, 0)

    def factory(self, name: str) -> Optional[Callable[[], ServedModel]]:
        """The model's registered factory, if any. Replica serving
        uses it to instantiate per-replica executables and to
        re-initialize an ejected replica's weights; note that entries
        registered through :meth:`add_model` resurrect the SAME
        instance (their factory is a capture of it), so true
        weight-level isolation needs an :meth:`add_factory`
        registration."""
        with self._lock:
            return self._factories.get(name)

    def get(self, name: str, version: str = "") -> ServedModel:
        with self._lock:
            model = self._models.get(name)
        if model is None:
            raise InferenceServerException(
                "request for unknown model: '%s' is not found" % name,
                status="NOT_FOUND",
            )
        if version and model.version != version:
            raise InferenceServerException(
                "request for unknown model version: '%s' version %s"
                % (name, version),
                status="NOT_FOUND",
            )
        return model

    def is_ready(self, name: str, version: str = "") -> bool:
        with self._lock:
            model = self._models.get(name)
            if model is None or self._state.get(name) != "READY":
                return False
            return not version or model.version == version

    def ready_models(self) -> List[ServedModel]:
        with self._lock:
            return [
                m for n, m in self._models.items()
                if self._state.get(n) == "READY"
            ]

    def index(self, ready_only: bool = False) -> pb.RepositoryIndexResponse:
        response = pb.RepositoryIndexResponse()
        with self._lock:
            for name in sorted(set(self._factories) | set(self._models)):
                state = self._state.get(name, "UNAVAILABLE")
                if ready_only and state != "READY":
                    continue
                model = self._models.get(name)
                response.models.add(
                    name=name,
                    version=model.version if model else "",
                    state=state,
                    reason=self._reason.get(name, ""),
                )
        return response
