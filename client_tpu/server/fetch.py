"""Overlapped device->host output fetch: the serving core's answer to
the ~67 ms output-relay tax (ROADMAP item 1).

Every serving path used to materialize outputs with a blocking
``np.asarray`` per tensor, serially: the first output's device->host
transfer had to retire before the second was even issued, and encode
could not start until the whole output dict was host-resident. On a
dense model the transfer — not the TPU — bounded the stage (BENCH r05:
``relay_fetch_ms_est`` ~67 ms against 0.8-3.4 ms device exec).

Three composable mechanisms replace that:

* **Overlapped non-blocking copies.** :meth:`OutputFetcher.start`
  issues ``copy_to_host_async`` on every device output up front, then
  lands each output on its own pool job — the transfers ride the
  device's DMA engines concurrently and the first landed output can
  encode (or wake its batch member) while later ones are still in
  flight. :meth:`InflightFetch.as_completed` yields outputs in LANDING
  order, which is what lets the batcher unblock each member as soon as
  *its* requested outputs land.

* **Chunked-parallel transfers.** An output at least twice
  ``chunk_bytes`` is split along its leading axis into device slices
  landed by concurrent jobs into one preallocated host buffer — a
  single huge tensor stops serializing on one transfer stream.
  Host-committed arrays (numpy, and jax arrays already on the cpu
  platform, whose ``np.asarray`` is a cached zero-copy view) are never
  chunked or pooled: slicing them would add copies and job overhead
  where the direct materialization is free.

* **Fetch-into-registered-region.** :func:`fetch_into` lands a
  tensor's bytes directly in a caller-provided writable buffer (a
  registered system-shm region), retiring the ``device -> host ndarray
  -> bytes object -> region`` double hop; :func:`host_view` serves a
  read-only byte view over the single host materialization (the
  TPU-arena serialization path's ``np.asarray(x).tobytes()`` fix).

Jobs never wait on other jobs, so the pool bounds concurrency but can
never deadlock; nothing here holds a lock across a transfer (the
per-fetch condition variable guards only completion bookkeeping —
tpulint lock-discipline).

Consumers: the dynamic batcher's fetch stage
(``client_tpu.server.batcher``), the direct/sequence paths in the core
(``client_tpu.server.core``), shared-memory output placement
(``client_tpu.server.memory``), and the TPU arena's serialization
paths (``client_tpu.server.tpu_arena``). Knobs:
``ModelConfig.overlapped_fetch`` (opt-out) and
``ModelConfig.fetch_chunk_bytes`` — see docs/zero_copy_fetch.md.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# Split threshold for chunked-parallel transfers: tensors at or above
# 2x this are landed as concurrent per-slice copies. 4 MiB keeps a
# logits-sized tensor whole (one job beats job overhead) while a
# 32 MiB activation rides 8 parallel lanes.
DEFAULT_CHUNK_BYTES = 4 << 20
# Pool width when the owner does not size it (the batcher passes its
# fetch_pool_workers; the core's shared fetcher uses this default).
DEFAULT_WORKERS = 4


def is_device_value(value) -> bool:
    """True for array-likes that need a host materialization step
    (anything ``__array__``-able that is not already numpy)."""
    return not isinstance(value, np.ndarray) and hasattr(value, "__array__")


def host_committed(value) -> bool:
    """True when host materialization is already free: numpy arrays,
    and jax arrays committed to the cpu platform (``np.asarray`` on
    those returns a cached zero-copy view — chunking or pooling them
    would add copies and job overhead to a no-op)."""
    if isinstance(value, np.ndarray):
        return True
    devices = getattr(value, "devices", None)
    if not callable(devices):
        return False
    try:
        return all(d.platform == "cpu" for d in devices())
    except Exception:  # noqa: BLE001 — unknown array-like: assume off-host
        return False


def start_async_copy(value) -> None:
    """Kick the device->host DMA without waiting on it (jax.Array's
    ``copy_to_host_async``): a later ``np.asarray`` finds the bytes
    already in flight or landed. No-op for array-likes without it."""
    hook = getattr(value, "copy_to_host_async", None)
    if hook is None:
        return
    try:
        hook()
    except Exception:  # noqa: BLE001 — an unlaunchable async copy just
        pass  # falls back to the blocking materialization


def host_array(value) -> np.ndarray:
    """ONE blocking host materialization, C-contiguous."""
    host = np.asarray(value)
    if not host.flags["C_CONTIGUOUS"]:
        host = np.ascontiguousarray(host)
    return host


def host_view(value) -> memoryview:
    """Read-only byte view over one host materialization of ``value``
    — the single-copy replacement for ``np.asarray(x).tobytes()``
    (which materializes and then copies the whole buffer AGAIN into a
    bytes object)."""
    host = host_array(value)
    if host.dtype.hasobject:
        raise TypeError("object arrays have no flat byte view")
    return host.reshape(-1).view(np.uint8).data


def fetch_into(value, dest) -> int:
    """Copy ``value``'s bytes into ``dest`` (a writable
    buffer/memoryview over a registered region) with no intermediate
    bytes object: one host materialization (a zero-copy view for
    host-committed arrays), then one copy straight into the region —
    the old path's whole-buffer ``tobytes()`` hop is gone. Returns the
    byte count written; the caller bounds-checks and sizes ``dest`` to
    at least that count."""
    start_async_copy(value)
    view = host_view(value)
    out = np.frombuffer(dest, dtype=np.uint8)
    n = len(view)
    if n > out.size:
        raise ValueError(
            "tensor of %d bytes exceeds the %d-byte landing buffer"
            % (n, out.size))
    out[:n] = np.frombuffer(view, dtype=np.uint8)
    return n


def offload_tree(tree):
    """Device pytree -> host (pinned-stand-in numpy) pytree with the
    overlapped-copy discipline: every device leaf's DMA is kicked
    first (``copy_to_host_async``), then the blocking materializations
    run against transfers already in flight — the weight page-out half
    of the hbm subsystem (docs/hbm.md). Host-committed leaves pass
    through as numpy views; non-array leaves pass through untouched."""
    try:
        import jax
    except Exception:  # noqa: BLE001 — no runtime: nothing to offload
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        if is_device_value(leaf) and not host_committed(leaf):
            start_async_copy(leaf)
    out = []
    for leaf in leaves:
        if is_device_value(leaf):
            out.append(host_array(leaf))
        elif isinstance(leaf, np.ndarray):
            out.append(leaf)
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def upload_tree(tree, device=None, chunk_bytes: int = 0,
                workers: int = 0):
    """Host pytree -> device pytree: the restore half of weight paging
    (docs/hbm.md) — :func:`offload_tree` run in reverse. All leaves
    upload concurrently on a transient pool, and each leaf at or above
    2x ``chunk_bytes`` additionally splits along its leading axis into
    parallel ``device_put`` slices, so a single huge weight tensor
    does not serialize the whole restore on one transfer stream.

    The job list is FLAT: this thread plans every chunk up front and
    submits one pool job per whole leaf or per slice, and is also the
    only thread that waits on futures. A job must never submit to and
    then wait on this same bounded pool — with every worker blocked
    inside a leaf waiting for slice jobs queued behind it, the pool
    deadlocks (the same jobs-never-wait-on-jobs rule as the landing
    pool)."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception:  # noqa: BLE001 — no runtime: hand back as-is
        return tree
    chunk_bytes = chunk_bytes if chunk_bytes > 0 else DEFAULT_CHUNK_BYTES
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not any(isinstance(leaf, np.ndarray) for leaf in leaves):
        return tree
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
            max_workers=(workers if workers > 0 else DEFAULT_WORKERS),
            thread_name_prefix="hbm-restore") as pool:
        uploads = []
        for leaf in leaves:
            if not isinstance(leaf, np.ndarray):
                uploads.append(None)
                continue
            plan = OutputFetcher._chunk_plan(leaf, chunk_bytes)
            if plan is None:
                uploads.append(pool.submit(jax.device_put, leaf, device))
            else:
                uploads.append([
                    pool.submit(jax.device_put, leaf[lo:hi], device)
                    for lo, hi in plan])
        out = []
        for leaf, upload in zip(leaves, uploads):
            if upload is None:
                out.append(leaf)
            elif isinstance(upload, list):
                out.append(jnp.concatenate(
                    [f.result() for f in upload], axis=0))
            else:
                out.append(upload.result())
    return jax.tree_util.tree_unflatten(treedef, out)


class _OutputHandle:
    """Completion state of one output's fetch. Immutable once it
    appears in the inflight completion order."""

    __slots__ = ("name", "value", "error", "chunks", "_dest",
                 "_remaining")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        # Number of parallel slice jobs landing this output (0 = one
        # whole-tensor job or an inline completion) — span attribute.
        self.chunks = 0
        self._dest = None
        self._remaining = 0

    @property
    def done(self) -> bool:
        return self.value is not None or self.error is not None


class InflightFetch:
    """All of one output dict's transfers, landing concurrently.

    Iterate :meth:`as_completed` to process outputs in LANDING order
    (how the batcher wakes each member the moment its outputs land);
    :meth:`result` waits for one output. Completion bookkeeping runs
    under the fetch's own condition variable; no transfer ever
    executes under it."""

    def __init__(self):
        self._cv = threading.Condition()
        self._handles: Dict[str, _OutputHandle] = {}
        self._order: List[str] = []

    @property
    def names(self) -> frozenset:
        return frozenset(self._handles)

    def _add(self, name: str) -> _OutputHandle:
        handle = _OutputHandle(name)
        self._handles[name] = handle
        return handle

    def _complete(self, name: str, value, error) -> None:
        with self._cv:
            handle = self._handles[name]
            if handle.done:
                return  # first completion wins (chunk-error races)
            handle.value = value
            handle.error = error
            handle._dest = None
            self._order.append(name)
            self._cv.notify_all()

    def _chunk_done(self, name: str, error: Optional[Exception] = None
                    ) -> None:
        with self._cv:
            handle = self._handles[name]
            if handle.done:
                return
            if error is not None:
                handle.error = error
                handle._dest = None
                self._order.append(name)
                self._cv.notify_all()
                return
            handle._remaining -= 1
            if handle._remaining == 0:
                handle.value = handle._dest
                handle._dest = None
                self._order.append(name)
                self._cv.notify_all()

    def as_completed(self) -> Iterator[_OutputHandle]:
        """Yields each output's handle in the order it landed."""
        served = 0
        total = len(self._handles)
        while served < total:
            with self._cv:
                while len(self._order) <= served:
                    self._cv.wait()
                name = self._order[served]
            served += 1
            yield self._handles[name]

    def wait(self, names=None) -> None:
        """Blocks until the named outputs (default: all) have landed
        or failed."""
        targets = (list(self._handles) if names is None
                   else [n for n in names if n in self._handles])
        for name in targets:
            handle = self._handles[name]
            with self._cv:
                while not handle.done:
                    self._cv.wait()

    def result(self, name: str) -> np.ndarray:
        """The landed host array for one output (raises its fetch
        error)."""
        self.wait((name,))
        handle = self._handles[name]
        if handle.error is not None:
            raise handle.error
        return handle.value


class OutputFetcher:
    """Owns the transfer pool and chunking policy: one per dynamic
    batcher (sized from its ``fetch_pool_workers``) plus one shared by
    the core's direct/sequence paths. Landing jobs never wait on other
    jobs, so the bounded pool can never deadlock — which is also why
    this pool is distinct from the batcher's orchestration pool (an
    orchestrating completion DOES wait on landing jobs)."""

    def __init__(self, workers: int = 0, chunk_bytes: int = 0):
        self._workers = workers if workers > 0 else DEFAULT_WORKERS
        self._chunk_bytes = (chunk_bytes if chunk_bytes > 0
                             else DEFAULT_CHUNK_BYTES)
        self._pool = None
        self._pool_lock = threading.Lock()
        self._stopped = False

    def _pool_or_none(self):
        """The lazily-created landing pool (None once shut down: the
        caller then lands inline, which is the drain path)."""
        with self._pool_lock:
            if self._stopped:
                return None
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=self._workers,
                    thread_name_prefix="relay-fetch")
            return self._pool

    def shutdown(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
            self._stopped = True
        if pool is not None:
            pool.shutdown(wait=True)

    def start(self, outputs: Dict[str, object], chunk_bytes: int = 0
              ) -> InflightFetch:
        """Issues every output's device->host transfer at once and
        returns the in-flight handle set. Host-committed outputs
        complete inline (their materialization is the zero-copy view
        the caller needed anyway); off-host outputs land on pool jobs,
        chunked-parallel past the split threshold."""
        chunk_bytes = chunk_bytes if chunk_bytes > 0 else self._chunk_bytes
        inflight = InflightFetch()
        for name in outputs:
            inflight._add(name)
        # Classify first, THEN issue async copies: whole-tensor
        # landings get their DMA kicked before the first blocking
        # materialization (the across-outputs overlap), but chunked
        # outputs must NOT get the full-buffer kick — their slices
        # carry their own transfers, and a redundant whole-tensor DMA
        # would contend with (and double) the chunked traffic.
        inline, whole, chunked = [], [], []
        for name, value in outputs.items():
            if not is_device_value(value) or host_committed(value):
                inline.append((name, value))
                continue
            plan = self._chunk_plan(value, chunk_bytes)
            if plan is None:
                whole.append((name, value))
            else:
                chunked.append((name, value, plan))
        for _name, value in whole:
            start_async_copy(value)
        jobs = []
        for name, value in inline:
            try:
                host = (value if isinstance(value, np.ndarray)
                        else host_array(value))
                inflight._complete(name, host, None)
            except Exception as e:  # noqa: BLE001 — per-output
                inflight._complete(name, None, e)
        for name, value in whole:
            jobs.append((self._land_whole, name, value, inflight))
        for name, value, plan in chunked:
            handle = inflight._handles[name]
            try:
                dest = np.empty(tuple(value.shape),
                                dtype=np.dtype(value.dtype))
            except Exception:  # noqa: BLE001 — undescribable dtype:
                jobs.append((self._land_whole, name, value, inflight))
                continue  # land whole instead of chunking
            handle._dest = dest
            handle._remaining = len(plan)
            handle.chunks = len(plan)
            for lo, hi in plan:
                jobs.append((self._land_chunk, name, value, dest, lo, hi,
                             inflight))
        pool = self._pool_or_none() if jobs else None
        for fn, *args in jobs:
            if pool is not None:
                try:
                    pool.submit(fn, *args)
                    continue
                except RuntimeError:  # pool shut down mid-drain
                    pool = None
            fn(*args)
        return inflight

    @staticmethod
    def _chunk_plan(value, chunk_bytes: int
                    ) -> Optional[List[Tuple[int, int]]]:
        """Leading-axis split for chunked-parallel landing, or None to
        land whole: needs a sliceable tensor of >=2 rows at >=2x the
        chunk size."""
        try:
            shape = tuple(getattr(value, "shape", ()) or ())
            if not shape or int(shape[0]) < 2:
                return None
            if getattr(value, "__getitem__", None) is None:
                return None
            nbytes = getattr(value, "nbytes", None)
            if nbytes is None:
                nbytes = int(np.prod(shape)) * np.dtype(value.dtype).itemsize
            nbytes = int(nbytes)
            if nbytes < 2 * chunk_bytes:
                return None
            rows = int(shape[0])
            rows_per = max(int(chunk_bytes // max(nbytes // rows, 1)), 1)
            plan = []
            lo = 0
            while lo < rows:
                hi = min(lo + rows_per, rows)
                plan.append((lo, hi))
                lo = hi
            return plan if len(plan) > 1 else None
        except Exception:  # noqa: BLE001 — unplannable: land whole
            return None

    @staticmethod
    def _land_whole(name, value, inflight: InflightFetch) -> None:
        try:
            inflight._complete(name, host_array(value), None)
        except Exception as e:  # noqa: BLE001 — error rides the handle
            inflight._complete(name, None, e)

    @staticmethod
    def _land_chunk(name, value, dest, lo, hi,
                    inflight: InflightFetch) -> None:
        if inflight._handles[name].done:
            return  # a sibling chunk already failed this output
        try:
            dest[lo:hi] = np.asarray(value[lo:hi])
            inflight._chunk_done(name)
        except Exception as e:  # noqa: BLE001 — error rides the handle
            inflight._chunk_done(name, e)
