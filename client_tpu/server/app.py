"""Server assembly + CLI: build a core with the builtin model zoo and
serve it over gRPC (and HTTP once enabled).

Run:  python -m client_tpu.server.app --grpc-port 8001 --models simple
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Optional, Sequence

from client_tpu.models import builtin_model_factories
from client_tpu.server.core import InferenceServerCore
from client_tpu.server.grpc_server import build_grpc_server
from client_tpu.server.repository import ModelRepository


def build_core(
    load_models: Optional[Sequence[str]] = None,
    tpu_arena=None,
    warmup: bool = True,
    cache_size: Optional[int] = None,
    tenant_quotas: Optional[str] = None,
) -> InferenceServerCore:
    repository = ModelRepository()
    for name, factory in builtin_model_factories(repository).items():
        repository.add_factory(name, factory)
    if tpu_arena is None:
        try:
            from client_tpu.server.tpu_arena import TpuArena

            tpu_arena = TpuArena()
        except Exception:
            tpu_arena = None  # no accelerator runtime available
    if cache_size is None:
        # Server-level response-cache byte budget (0 disables); the
        # env var covers embedded launches with no CLI surface.
        env = os.environ.get("CLIENT_TPU_CACHE_SIZE", "")
        cache_size = int(env) if env else None
    quota_manager = None
    if tenant_quotas is None:
        # Per-tenant admission quotas (same env-var pattern as the
        # cache budget for embedded launches).
        tenant_quotas = os.environ.get("CLIENT_TPU_TENANT_QUOTAS", "")
    if tenant_quotas:
        from client_tpu.server.qos import TenantQuotaManager

        quota_manager = TenantQuotaManager.from_spec(tenant_quotas)
    core = InferenceServerCore(repository, tpu_arena=tpu_arena,
                               cache_size=cache_size,
                               tenant_quotas=quota_manager)
    for name in load_models or ():
        # Through the core so every startup load lands in the device
        # ledger (weights row) with warmup compiles attributed.
        core.load_model(name, warmup=warmup)
    return core


class ServerHandle:
    """A running gRPC (+ arena service) server endpoint."""

    def __init__(self, core: InferenceServerCore, grpc_server, address: str):
        self.core = core
        self.grpc_server = grpc_server
        self.address = address

    def stop(self, grace: float = 1.0):
        # Health flips to not-ready BEFORE the listener stops: load
        # balancers polling /v2/health/ready see the drain and stop
        # routing while in-flight requests finish under `grace`.
        self.core.ready = False
        self.grpc_server.stop(grace)
        self.core.shutdown()


def start_grpc_server(
    load_models: Optional[Sequence[str]] = None,
    address: str = "127.0.0.1:0",
    core: Optional[InferenceServerCore] = None,
    max_workers: int = 96,
    aio: Optional[bool] = None,
) -> ServerHandle:
    """Start a server on ``address`` (port 0 = ephemeral); returns a
    handle with the bound address.

    ``aio`` selects the asyncio-transport front-end (the default: it
    clears ~1.8x the sync thread-pool server's request rate with the
    same servicer); pass ``False`` — or set CLIENT_TPU_GRPC_AIO=0 — for
    the classic sync server.
    """
    if aio is None:
        aio = os.environ.get("CLIENT_TPU_GRPC_AIO", "1") != "0"
    if core is None:
        core = build_core(load_models)
    extra = []
    if core.memory.arena is not None:
        from client_tpu.server.arena_service import arena_servicer_entry

        extra.append(arena_servicer_entry(core.memory.arena))
    host = address.rsplit(":", 1)[0]

    def publish_arena_route(port: int) -> None:
        # Handles minted once serving starts carry this address, making
        # them redeemable from other hosts via the DCN pull path —
        # which is why this runs post-bind but PRE-serve (a handle
        # minted by the first request must already be routed).
        arena = core.memory.arena
        if arena is None or arena.public_url:
            return
        from client_tpu.server.arena_pull import resolve_arena_route

        route = resolve_arena_route("%s:%d" % (host, port))
        if route:
            arena.set_public_url(route)

    if aio:
        from client_tpu.server.grpc_server import AioGrpcServerThread

        server = AioGrpcServerThread(core, address, extra_servicers=extra,
                                     max_workers=max_workers,
                                     on_bound=publish_arena_route)
        port = server.port
    else:
        server = build_grpc_server(core, address=None,
                                   max_workers=max_workers,
                                   extra_servicers=extra)
        port = server.add_insecure_port(address)
        if port == 0:
            raise RuntimeError("unable to bind %s" % address)
        publish_arena_route(port)
        server.start()
    return ServerHandle(core, server, "%s:%d" % (host, port))


def main(argv=None):
    parser = argparse.ArgumentParser(description="client_tpu inference server")
    parser.add_argument("--grpc-port", type=int, default=8001)
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--no-http", action="store_true")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument(
        "--models", nargs="*", default=["simple"],
        help="models to load at startup (others load on demand)",
    )
    parser.add_argument(
        "--cache-size", type=int, default=None,
        help="response-cache byte budget shared across models "
             "(0 disables; default 64 MiB; models opt in via "
             "response_cache.enable)",
    )
    parser.add_argument(
        "--tenant-quotas", default=None,
        help="per-tenant admission quotas, e.g. "
             "'default=rate:100,burst:20,concurrency:8;bulk=rate:10' "
             "(rejects are 429/RESOURCE_EXHAUSTED with Retry-After "
             "from the bucket refill time; tenant identity comes from "
             "the `tenant` request parameter, the x-tenant-id HTTP "
             "header, or `tenant` gRPC metadata)",
    )
    args = parser.parse_args(argv)

    core = build_core(args.models, cache_size=args.cache_size,
                      tenant_quotas=args.tenant_quotas)
    handle = start_grpc_server(
        core=core, address="%s:%d" % (args.host, args.grpc_port)
    )
    print("gRPC server listening on %s" % handle.address, flush=True)
    http_runner = None
    if not args.no_http:
        try:
            from client_tpu.server.http_server import start_http_server_thread

            http_runner = start_http_server_thread(
                core, host=args.host, port=args.http_port
            )
            print(
                "HTTP server listening on %s:%d" % (args.host, args.http_port),
                flush=True,
            )
        except ImportError as e:
            print("HTTP server unavailable: %s" % e, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.stop()
        if http_runner is not None:
            http_runner.stop()


if __name__ == "__main__":
    main()
