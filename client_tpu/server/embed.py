"""Embedding surface for hosting the server core inside a native
process (no RPC).

The C++ perf harness's ``--service-kind in_process`` backend embeds
CPython, imports this module, and drives inference through the
serialized-protobuf functions below — the TPU-native analogue of the
reference's ``triton_c_api`` backend, which dlopens tritonserver and
calls its C API directly
(/root/reference/src/c++/perf_analyzer/client_backend/triton_c_api/
triton_loader.cc:526-690). Keeping the exchange at proto-bytes level
means the embedding layer needs no Python object marshalling beyond
``bytes`` <-> ``std::string``.

All functions are module-level and hold no GIL assumptions beyond the
caller owning it for the duration of each call (PyGILState_Ensure in
the C++ backend).
"""

from __future__ import annotations

import json
from typing import Optional

from client_tpu import status_map
from client_tpu.protocol import inference_pb2 as pb

_core = None


def init(models_csv: str = "") -> None:
    """Builds the server core and warms the named models (comma
    separated; empty = registry defaults, loaded lazily)."""
    global _core
    if _core is not None:
        return
    from client_tpu.server.app import build_core

    names = [m for m in models_csv.split(",") if m]
    _core = build_core(names)


def _require_core():
    if _core is None:
        raise RuntimeError("embed.init() has not been called")
    return _core


def infer(request_bytes: bytes) -> bytes:
    """Serialized ModelInferRequest -> serialized ModelInferResponse.
    Errors surface as InferenceServerException for the C++ layer to
    format (message carries the [STATUS] prefix)."""
    core = _require_core()
    request = pb.ModelInferRequest()
    request.ParseFromString(request_bytes)
    return core.infer(request).SerializeToString()


def server_metadata_json() -> str:
    meta = _require_core().server_metadata()
    return json.dumps({
        "name": meta.name,
        "version": meta.version,
        "extensions": list(meta.extensions),
    })


def model_metadata_json(name: str, version: str = "") -> str:
    meta = _require_core().model_metadata(name, version)
    def tensors(specs):
        return [{"name": t.name, "datatype": t.datatype,
                 "shape": list(t.shape)} for t in specs]
    return json.dumps({
        "name": meta.name,
        "versions": list(meta.versions),
        "platform": meta.platform,
        "inputs": tensors(meta.inputs),
        "outputs": tensors(meta.outputs),
    })


def model_config_json(name: str, version: str = "") -> str:
    response = _require_core().model_config(name, version)
    from google.protobuf import json_format

    # The bare config object (not the response wrapper), snake_case:
    # the native ModelParser reads reference-wire keys like
    # "max_batch_size" directly (model_parser.cc Parse).
    return json_format.MessageToJson(
        response.config, preserving_proto_field_name=True)


def model_statistics_json(name: str = "") -> str:
    # Hand-rolled (not json_format): protobuf JSON encodes (u)int64 as
    # strings, which the native harness's numeric parsing rejects.
    stats = _require_core().model_statistics(name, "")

    def dur(d):
        return {"count": d.count, "ns": d.ns}

    return json.dumps({"model_stats": [
        {
            "name": m.name,
            "version": m.version,
            "inference_count": m.inference_count,
            "execution_count": m.execution_count,
            "cache_hit_count": m.cache_hit_count,
            "cache_miss_count": m.cache_miss_count,
            "inference_stats": {
                "success": dur(m.inference_stats.success),
                "fail": dur(m.inference_stats.fail),
                "queue": dur(m.inference_stats.queue),
                "compute_input": dur(m.inference_stats.compute_input),
                "compute_infer": dur(m.inference_stats.compute_infer),
                "compute_output": dur(m.inference_stats.compute_output),
                "cache_hit": dur(m.inference_stats.cache_hit),
                "cache_miss": dur(m.inference_stats.cache_miss),
            },
        }
        for m in stats.model_stats
    ]})


def register_system_shared_memory(name: str, key: str, byte_size: int,
                                  offset: int = 0) -> None:
    _require_core().memory.register_system(name, key, offset, byte_size)


def register_tpu_shared_memory(name: str, raw_handle: bytes,
                               device_id: int, byte_size: int) -> None:
    _require_core().memory.register_tpu(
        name, raw_handle, device_id, byte_size)


def unregister_system_shared_memory(name: str = "") -> None:
    _require_core().memory.unregister_system(name or None)


def unregister_tpu_shared_memory(name: str = "") -> None:
    _require_core().memory.unregister_tpu(name or None)


def set_arena_public_url(url: str) -> None:
    """Publishes the front-end's bound address into every handle the
    arena mints from now on (call post-bind, pre-serve), making them
    redeemable from other hosts via the DCN pull path. Same routing
    policy as the Python front-end (arena_pull.resolve_arena_route);
    a first-set wins."""
    from client_tpu.server.arena_pull import resolve_arena_route

    arena = _require_core().memory.arena
    if arena is None or arena.public_url:
        return
    route = resolve_arena_route(url)
    if route:
        arena.set_public_url(route)


def tpu_arena_allocate(byte_size: int, device_id: int = 0) -> bytes:
    """Allocates an HBM arena region in-process; returns the raw
    handle bytes (what the gRPC arena service would return)."""
    arena = _require_core().memory.arena
    if arena is None:
        # Clears only on an operator restart with an arena configured.
        raise status_map.retryable_error(
            "server has no TPU arena; TPU shared memory unavailable",
            retry_after_s=30.0)
    return arena.create_region(byte_size, device_id)


def load_model(name: str) -> None:
    _require_core().load_model(name)


#==============================================================================
# Generic gRPC dispatch: the native server front-end (native/server/)
# terminates HTTP/2 + gRPC framing in C++ and forwards each call here
# by its wire path, so transport and servicer logic stay in one place.

class GrpcAbort(Exception):
    """An RPC failure carrying the numeric gRPC status code. __str__
    formats as "[GRPC:<code>] <details>" which the native bridge
    parses back into (code, message) for the grpc-status trailer."""

    def __init__(self, code: int, details: str):
        super().__init__("[GRPC:%d] %s" % (code, details))
        self.code = code
        self.details = details


class _AbortContext:
    """Stand-in for grpc.ServicerContext: servicers only ever call
    abort() (which must raise) on it."""

    def abort(self, code, details):
        raise GrpcAbort(code.value[0], details)

    def set_code(self, code):  # pragma: no cover - servicers use abort
        pass

    def set_details(self, details):  # pragma: no cover
        pass


_registry = None  # path -> (request_cls, handler, server_streaming)


def _grpc_registry():
    global _registry
    if _registry is not None:
        return _registry
    core = _require_core()
    from client_tpu.protocol import service as svc
    from client_tpu.server.grpc_server import InferenceServicer

    servicer = InferenceServicer(core)
    registry = {}
    for name, req_t, _resp_t, _cstream, sstream in svc._METHODS:
        path = "/%s/%s" % (svc.SERVICE_NAME, name)
        registry[path] = (req_t, getattr(servicer, name), sstream)
    if core.memory.arena is not None:
        from client_tpu.server import arena_service

        arena_servicer = arena_service.TpuArenaServicer(core.memory.arena)
        for name, req_t, _resp_t in arena_service._METHODS:
            path = "/%s/%s" % (arena_service.SERVICE_NAME, name)
            registry[path] = (req_t, getattr(arena_servicer, name), False)
        for name, req_t, _resp_t in arena_service._STREAM_METHODS:
            # Server-streaming with a UNARY request (PullRegion). The
            # embed stream dispatch hands every handler a request
            # iterator (bidi shape); adapt it to the unary-request
            # signature the arena servicer uses.
            path = "/%s/%s" % (arena_service.SERVICE_NAME, name)

            def _adapt(request_iter, context,
                       _method=getattr(arena_servicer, name)):
                return _method(next(iter(request_iter)), context)

            registry[path] = (req_t, _adapt, True)
    _registry = registry
    return registry


def grpc_method_kind(path: str) -> str:
    """"unary", "stream", or "" for an unknown path."""
    entry = _grpc_registry().get(path)
    if entry is None:
        return ""
    return "stream" if entry[2] else "unary"


def grpc_call(path: str, request_bytes: bytes) -> bytes:
    """Dispatches one unary RPC by wire path; returns the serialized
    response. Unknown paths / servicer aborts raise GrpcAbort."""
    entry = _grpc_registry().get(path)
    if entry is None or entry[2]:
        raise GrpcAbort(12, "unknown or non-unary method %s" % path)
    req_t, handler, _ = entry
    request = req_t()
    request.ParseFromString(request_bytes)
    response = handler(request, _AbortContext())
    return response.SerializeToString()


def http_call(method: str, path: str, headers_json: str,
              body: bytes) -> tuple:
    """REST twin of grpc_call for the native HTTP/1.1 front-end:
    returns (status:int, headers_json:str, body:bytes). Header names
    in ``headers_json`` must be lower-cased by the transport."""
    import json as _json

    from client_tpu.server import http_embed

    status, headers, payload = http_embed.http_call(
        _require_core(), method, path,
        _json.loads(headers_json) if headers_json else {}, body)
    return status, _json.dumps(headers), payload


def http_cancel(request_id: str) -> bool:
    """Client-disconnect hook for the native HTTP/1.1 front-end: when
    the transport sees the client socket hit EOF while a unary request
    is still in flight, it cancels by the request id it parsed from
    the wire. True when an in-flight request was found and flipped."""
    from client_tpu.server import cancel as cancel_mod

    return _require_core().cancel_request(
        request_id, reason=cancel_mod.REASON_CLIENT_DISCONNECT)


def grpc_stream_call(path: str, request_bytes: bytes) -> list:
    """Dispatches one message of a bidi-streaming RPC; returns the
    list of serialized responses it produced. Stream RPCs here map
    each request independently (ModelStreamInfer semantics), so no
    cross-call session state is needed.

    NOTE: this variant buffers — a decoupled model's full response
    stream materializes before anything returns. The native transport
    uses grpc_stream_call_emit for incremental delivery; this remains
    for in-process callers that want the collected list.
    """
    entry = _grpc_registry().get(path)
    if entry is None or not entry[2]:
        raise GrpcAbort(12, "unknown or non-stream method %s" % path)
    req_t, handler, _ = entry
    request = req_t()
    request.ParseFromString(request_bytes)
    responses = handler(iter([request]), _AbortContext())
    return [r.SerializeToString() for r in responses]


def grpc_stream_call_emit(path: str, request_bytes: bytes, emit) -> None:
    """Incremental twin of grpc_stream_call: calls ``emit(serialized)``
    for each response as the handler produces it, so the native
    front-end writes decoupled-model responses (LLM tokens) to the
    wire one by one instead of in one end-of-generation burst. A
    falsy return from ``emit`` means the peer is gone — stop
    producing (the servicer's generator close() cancels the
    underlying request)."""
    entry = _grpc_registry().get(path)
    if entry is None or not entry[2]:
        raise GrpcAbort(12, "unknown or non-stream method %s" % path)
    req_t, handler, _ = entry
    request = req_t()
    request.ParseFromString(request_bytes)
    responses = handler(iter([request]), _AbortContext())
    try:
        for r in responses:
            if not emit(r.SerializeToString()):
                break
    finally:
        close = getattr(responses, "close", None)
        if close is not None:
            close()


def shutdown() -> None:
    """Unloads every ready model, then runs the core's process-level
    teardown (batcher stop + buffered-trace flush) and drops the
    core."""
    global _core, _registry
    _registry = None  # dispatch registry holds servicers bound to _core
    if _core is None:
        return
    core, _core = _core, None
    for name in [m.name for m in core.repository.ready_models()]:
        try:
            core.unload_model(name)
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
    try:
        core.shutdown()
    except Exception:  # noqa: BLE001
        pass
