"""Multi-tenant quality-of-service primitives.

Two independent mechanisms share this module because they are enforced
at the same boundary (request admission) and report into the same
observability surfaces:

* **Priority classes** — the `priority` request parameter all four
  clients already send (and the server silently dropped before this
  module). :func:`coerce_priority` is the single source of truth for
  its wire semantics: levels are ``1..priority_levels`` with 1 the
  highest class, 0/absent falls back to the model's
  ``default_priority_level``, string/double forms are coerced like the
  batcher's ``timeout`` parameter, and out-of-range values are
  rejected INVALID_ARGUMENT instead of being ignored (an ignored
  priority is a silent QoS downgrade the sender cannot observe).

* **Tenant quotas** — a token-bucket rate limiter plus a concurrency
  cap per tenant identity, enforced by :class:`TenantQuotaManager` at
  the front door of ``core.infer`` (before the model is even
  acquired). Tenant identity comes from the ``tenant`` request
  parameter; the HTTP front-end maps an ``x-tenant-id`` header and the
  gRPC front-end a ``tenant`` metadata key onto that parameter, so all
  transports converge on one wire form. Rejects surface as
  RESOURCE_EXHAUSTED (HTTP 429) carrying a ``Retry-After`` derived
  from the bucket's refill time — the PR-2 RetryPolicy sleeps at least
  that long before retrying, which turns quota pressure into client
  backpressure instead of a retry storm.

Quotas are configured per server via a spec string
(``--tenant-quotas`` / the CLIENT_TPU_TENANT_QUOTAS env var):

    default=rate:100,burst:20,concurrency:8;bulk=rate:10,burst:5

Entries are ``tenant=knob:value,...`` separated by ``;``. The
``default`` entry is the template every unlisted tenant gets its own
bucket from (requests without an identity share the ``anonymous``
tenant's bucket). ``rate`` is tokens (requests) per second, ``burst``
the bucket size (defaults to max(rate, 1)), ``concurrency`` the
in-flight cap; 0 disables that knob for the tenant.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional

from client_tpu.utils import InferenceServerException

ENV_VAR = "CLIENT_TPU_TENANT_QUOTAS"

# Identity assigned to requests that carry no tenant parameter/header:
# they are still governed (by the default policy) — an unlabeled flood
# must not bypass admission just by omitting the header.
ANONYMOUS_TENANT = "anonymous"

# Tenant identity is client-supplied: a client rotating the value per
# request must not grow server state/metric cardinality without bound.
# Once this many DYNAMIC (not explicitly configured) tenants exist,
# further new identities share one overflow bucket.
MAX_TRACKED_TENANTS = 1024
OVERFLOW_TENANT = "overflow"


@dataclasses.dataclass
class ShedDirective:
    """An admission-coupled shed order from the autoscale controller.

    Raised when a model's SLO is burning even at max replica scale:
    growing capacity is no longer an option, so the lowest priority
    class sheds AT THE DOOR (the PR-7 watermark path) instead of
    queueing work the fleet cannot absorb. ``retry_after_s`` is the
    controller's predicted recovery time — an honest Retry-After the
    shed response carries so well-behaved clients pace their return
    instead of hammering a saturated fleet. Cleared (``active=False``)
    the first tick the verdict recovers."""

    active: bool = False
    retry_after_s: float = 0.0
    reason: str = ""
    since: float = 0.0


def coerce_int(value) -> int:
    """int() that also accepts double/decimal-string wire forms (HTTP
    clients serialize numeric params as strings or doubles). The ONE
    numeric-param coercion — `timeout` (batcher) and `priority` (here)
    must accept identical wire forms."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return int(float(value))


def coerce_priority(value, priority_levels: int,
                    default_level: int = 0) -> int:
    """Normalizes one request's ``priority`` parameter to a level in
    ``1..priority_levels`` (1 = highest). Accepts int/str/double wire
    forms (HTTP clients send numeric params as strings or doubles,
    exactly the `timeout` hardening gap this PR closes for priority).
    0/absent selects ``default_level`` (or the middle level when that
    is 0 too). Raises INVALID_ARGUMENT for negative, over-max, or
    non-numeric values — dropping them silently would downgrade the
    request's service class without the sender ever knowing."""
    if priority_levels <= 0:
        return 0
    if value is None:
        level = 0
    else:
        try:
            level = coerce_int(value)
        except (TypeError, ValueError):
            raise InferenceServerException(
                "priority '%s' is not numeric (accepted range: "
                "0..%d, 1 = highest, 0 = model default)"
                % (value, priority_levels),
                status="INVALID_ARGUMENT") from None
    if level == 0:
        level = default_level or (priority_levels + 1) // 2
        return min(max(level, 1), priority_levels)
    if level < 0 or level > priority_levels:
        raise InferenceServerException(
            "priority %d out of range (accepted range: 0..%d, "
            "1 = highest, 0 = model default)" % (level, priority_levels),
            status="INVALID_ARGUMENT")
    return level


class TenantPolicy:
    """Per-tenant quota knobs. rate_per_s=0 means no rate limit,
    concurrency=0 no in-flight cap; burst defaults to max(rate, 1)."""

    __slots__ = ("rate_per_s", "burst", "concurrency")

    def __init__(self, rate_per_s: float = 0.0, burst: float = 0.0,
                 concurrency: int = 0):
        self.rate_per_s = max(float(rate_per_s), 0.0)
        self.burst = float(burst) if burst > 0 else max(self.rate_per_s, 1.0)
        self.concurrency = max(int(concurrency), 0)

    @property
    def enforced(self) -> bool:
        return self.rate_per_s > 0 or self.concurrency > 0


class _TenantState:
    """One tenant's bucket + counters (lock held by the manager)."""

    __slots__ = ("policy", "tokens", "last_refill_s", "inflight",
                 "admitted", "rejected", "completed", "failed",
                 "total_ns")

    def __init__(self, policy: TenantPolicy, now_s: float):
        self.policy = policy
        self.tokens = policy.burst
        self.last_refill_s = now_s
        self.inflight = 0
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        self.total_ns = 0


class TenantQuotaManager:
    """Token-bucket rate + concurrency admission control per tenant.

    ``acquire`` spends one token (refilled continuously at the
    tenant's rate, capped at burst) and one in-flight slot; a reject
    raises RESOURCE_EXHAUSTED with ``retry_after_s`` set to the time
    until the bucket holds a full token again — the value the
    front-ends serialize as Retry-After / retry-after metadata.
    ``release`` returns the slot and records latency. All state lives
    behind one lock; the per-request work is O(1)."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 default: Optional[TenantPolicy] = None,
                 clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._default = default or TenantPolicy()
        self._policies = dict(policies or {})
        self._tenants: Dict[str, _TenantState] = {}

    @property
    def enabled(self) -> bool:
        return self._default.enforced or any(
            p.enforced for p in self._policies.values())

    @classmethod
    def from_spec(cls, spec: str) -> "TenantQuotaManager":
        """Parse ``"default=rate:100,burst:20,concurrency:8;bulk=
        rate:10"``; unknown knobs fail loudly."""
        policies: Dict[str, TenantPolicy] = {}
        default = None
        for entry in (spec or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            tenant, sep, knobs = entry.partition("=")
            if not sep:
                raise ValueError(
                    "tenant-quota entry '%s' is not tenant=knobs" % entry)
            kwargs: Dict[str, float] = {}
            for knob in knobs.split(","):
                knob = knob.strip()
                if not knob:
                    continue
                key, sep, value = knob.partition(":")
                if not sep:
                    raise ValueError(
                        "tenant-quota knob '%s' is not key:value" % knob)
                key = key.strip()
                if key == "rate":
                    kwargs["rate_per_s"] = float(value)
                elif key == "burst":
                    kwargs["burst"] = float(value)
                elif key == "concurrency":
                    kwargs["concurrency"] = int(value)
                else:
                    raise ValueError(
                        "unknown tenant-quota knob '%s'" % key)
            policy = TenantPolicy(**kwargs)
            tenant = tenant.strip()
            if tenant == "default":
                default = policy
            else:
                policies[tenant] = policy
        return cls(policies, default)

    def _state_for(self, tenant: str, now_s: float) -> _TenantState:
        state = self._tenants.get(tenant)
        if state is None:
            if tenant not in self._policies \
                    and len(self._tenants) >= MAX_TRACKED_TENANTS:
                # Cardinality bound: rotating client-supplied identities
                # collapse into one shared overflow bucket (explicitly
                # configured tenants always keep their own).
                tenant = OVERFLOW_TENANT
                state = self._tenants.get(tenant)
                if state is not None:
                    return state
            policy = self._policies.get(tenant, self._default)
            state = self._tenants[tenant] = _TenantState(policy, now_s)
        return state

    def _refill(self, state: _TenantState, now_s: float) -> None:
        if state.policy.rate_per_s <= 0:
            return
        elapsed = now_s - state.last_refill_s
        if elapsed > 0:
            state.tokens = min(
                state.tokens + elapsed * state.policy.rate_per_s,
                state.policy.burst)
            state.last_refill_s = now_s

    def acquire(self, tenant: str) -> str:
        """Admit one request for ``tenant`` or raise RESOURCE_EXHAUSTED
        (HTTP 429) with ``retry_after_s`` set from the bucket refill
        time. Returns the RESOLVED identity (== tenant, or
        OVERFLOW_TENANT once the cardinality bound folds new dynamic
        identities together) — callers MUST pair a successful acquire
        with release() on that resolved name."""
        now_s = self._clock()
        with self._lock:
            state = self._state_for(tenant, now_s)
            if self._tenants.get(tenant) is not state:
                tenant = OVERFLOW_TENANT
            policy = state.policy
            self._refill(state, now_s)
            if policy.concurrency > 0 \
                    and state.inflight >= policy.concurrency:
                state.rejected += 1
                retry_after = self._retry_after_locked(state)
                raise self._reject(tenant, "concurrency limit %d"
                                   % policy.concurrency, retry_after)
            if policy.rate_per_s > 0:
                if state.tokens < 1.0:
                    state.rejected += 1
                    retry_after = (1.0 - state.tokens) / policy.rate_per_s
                    raise self._reject(
                        tenant, "rate limit %g req/s" % policy.rate_per_s,
                        retry_after)
                state.tokens -= 1.0
            state.inflight += 1
            state.admitted += 1
            return tenant

    @staticmethod
    def _retry_after_locked(state: _TenantState) -> float:
        # Concurrency rejects have no refill clock; advise one mean
        # service time's worth of backoff from the observed latency,
        # floored at 50 ms so an all-zero history still backs off.
        if state.completed > 0:
            return max(state.total_ns / state.completed / 1e9, 0.05)
        return 0.05

    @staticmethod
    def _reject(tenant: str, reason: str,
                retry_after_s: float) -> InferenceServerException:
        error = InferenceServerException(
            "tenant '%s' over quota (%s); retry after %.3fs"
            % (tenant, reason, retry_after_s),
            status="RESOURCE_EXHAUSTED")
        # Serialized as the HTTP Retry-After header / gRPC retry-after
        # trailing metadata; RetryPolicy sleeps at least this long.
        error.retry_after_s = max(retry_after_s, 0.001)
        return error

    def release(self, tenant: str, ok: bool, duration_ns: int) -> None:
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:  # release without acquire: stats only
                return
            if state.inflight > 0:
                state.inflight -= 1
            if ok:
                state.completed += 1
                state.total_ns += max(int(duration_ns), 0)
            else:
                state.failed += 1

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant counters + gauges for /metrics and statistics."""
        now_s = self._clock()
        out: Dict[str, dict] = {}
        with self._lock:
            for tenant, state in self._tenants.items():
                self._refill(state, now_s)
                out[tenant] = {
                    "admitted": state.admitted,
                    "rejected": state.rejected,
                    "completed": state.completed,
                    "failed": state.failed,
                    "total_ns": state.total_ns,
                    "inflight": state.inflight,
                    "tokens": state.tokens,
                }
        return out
