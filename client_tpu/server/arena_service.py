"""gRPC glue for the TPU arena service (hosted on the same server
port as the inference service)."""

from __future__ import annotations

import grpc

from client_tpu import status_map
from client_tpu.protocol import arena_pb2
from client_tpu.server.tpu_arena import TpuArena
from client_tpu.utils import InferenceServerException

SERVICE_NAME = "inference.TpuArenaService"

_METHODS = [
    ("CreateRegion", arena_pb2.CreateRegionRequest,
     arena_pb2.CreateRegionResponse),
    ("WriteRegion", arena_pb2.WriteRegionRequest,
     arena_pb2.WriteRegionResponse),
    ("ReadRegion", arena_pb2.ReadRegionRequest,
     arena_pb2.ReadRegionResponse),
    ("DestroyRegion", arena_pb2.DestroyRegionRequest,
     arena_pb2.DestroyRegionResponse),
    ("ListRegions", arena_pb2.ListRegionsRequest,
     arena_pb2.ListRegionsResponse),
]

# Server-streaming methods (the DCN pull path).
_STREAM_METHODS = [
    ("PullRegion", arena_pb2.PullRegionRequest,
     arena_pb2.PullRegionChunk),
]

class TpuArenaStub:
    def __init__(self, channel):
        for name, req_t, resp_t in _METHODS:
            setattr(
                self, name,
                channel.unary_unary(
                    "/%s/%s" % (SERVICE_NAME, name),
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )
        for name, req_t, resp_t in _STREAM_METHODS:
            setattr(
                self, name,
                channel.unary_stream(
                    "/%s/%s" % (SERVICE_NAME, name),
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


class TpuArenaServicer:
    def __init__(self, arena: TpuArena):
        self._arena = arena

    def _abort(self, context, error: InferenceServerException):
        context.abort(
            status_map.grpc_code(error.status()),
            error.message(),
        )

    def CreateRegion(self, request, context):
        try:
            raw_handle = self._arena.create_region(
                request.byte_size, request.device_id
            )
            import json

            region_id = json.loads(raw_handle)["region_id"]
            return arena_pb2.CreateRegionResponse(
                raw_handle=raw_handle, region_id=region_id
            )
        except InferenceServerException as e:
            self._abort(context, e)

    def WriteRegion(self, request, context):
        try:
            self._arena.write(
                request.region_id, request.offset, request.data,
                request.datatype, list(request.shape) or None,
            )
            return arena_pb2.WriteRegionResponse()
        except InferenceServerException as e:
            self._abort(context, e)

    def ReadRegion(self, request, context):
        try:
            data = self._arena.read(
                request.region_id, request.offset, request.byte_size
            )
            # read() may serve a zero-copy memoryview (single-segment
            # window); the proto boundary is where it becomes bytes.
            return arena_pb2.ReadRegionResponse(data=bytes(data))
        except InferenceServerException as e:
            self._abort(context, e)

    def DestroyRegion(self, request, context):
        self._arena.destroy_region(request.region_id)
        return arena_pb2.DestroyRegionResponse()

    def ListRegions(self, request, context):
        response = arena_pb2.ListRegionsResponse()
        for region_id, device_id, byte_size in self._arena.list_regions():
            response.regions.add(
                region_id=region_id, device_id=device_id, byte_size=byte_size
            )
        return response

    def PullRegion(self, request, context):
        """Owner side of the DCN pull: authenticate the handle, then
        stream typed segments (client_tpu.server.arena_pull)."""
        from client_tpu.server.arena_pull import iter_region_chunks

        try:
            yield from iter_region_chunks(
                self._arena, request.raw_handle, request.chunk_bytes)
        except InferenceServerException as e:
            self._abort(context, e)


def add_TpuArenaServicer_to_server(servicer: TpuArenaServicer, server):
    handlers = {}
    for name, req_t, resp_t in _METHODS:
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    for name, req_t, resp_t in _STREAM_METHODS:
        handlers[name] = grpc.unary_stream_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def arena_servicer_entry(arena: TpuArena):
    """(add_fn, servicer) pair for build_grpc_server's
    extra_servicers."""
    return (add_TpuArenaServicer_to_server, TpuArenaServicer(arena))
