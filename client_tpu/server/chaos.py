"""Fault injection for the server request path.

Chaos is configured per process (``configure`` / the CLIENT_TPU_CHAOS
environment variable) and evaluated by :func:`inject`, which the server
core calls once per inference request. Three fault kinds:

* ``latency_ms`` — added service latency (sleep before execution).
* ``error_rate`` — fraction of requests failed with UNAVAILABLE, the
  shape a crashing backend or evicted pod produces.
* ``drop_rate`` — fraction of requests failed as *connection drops*:
  the HTTP front-end closes the TCP transport mid-request (the client
  sees a reset, not an error body); gRPC surfaces UNAVAILABLE with a
  drop marker. Raised as :class:`ChaosDropError` so front-ends can
  distinguish a drop from an ordinary injected error.
* ``hang_ms`` — a stall: every matching execution sleeps this long
  (deterministic, no roll), the shape a wedged device queue produces.
  Sized above a replica's watchdog deadline it is what the watchdog
  ejection path exists to catch.
* ``abandon_rate`` — fraction of requests whose *caller walks away*
  mid-flight: the request's CancelToken is cancelled
  ``abandon_after_ms`` after injection (a client disconnect, seen
  from the server). Unlike drop_rate the request was healthy — this
  is the fault the cancellation subsystem converts from wasted device
  time into freed capacity, and what the cancel smoke's abandoned
  storm replays.

Spec strings (``--chaos`` / CLIENT_TPU_CHAOS) are comma-separated
``key=value`` pairs, e.g. ``"latency_ms=50,error_rate=0.1,seed=7"``.
An optional ``models=a+b`` entry restricts injection to those models.
An optional ``replica=model:index`` entry retargets the config at
exactly ONE replica of an instance-group model: the faults then fire
only at the replica layer's inject (which passes ``replica_id``) and
never at the request-level inject — degrading one fault domain while
its siblings and the front-of-house path stay clean. An optional
``device=<id>`` entry targets one DEVICE instead: the faults fire at
any replica execution whose device set contains that chip — for a
mesh-sharded model this is exactly one chip of one slice, the
kill-one-chip experiment that must eject the whole slice while its
sibling slices keep serving.

Everything is deterministic under ``seed`` so a chaos run is
reproducible — the property that turns "it degrades gracefully" into a
regression-gated measurement.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

from client_tpu.utils import InferenceServerException
from client_tpu import status_map

ENV_VAR = "CLIENT_TPU_CHAOS"


class ChaosDropError(InferenceServerException):
    """An injected connection drop. Subclasses the server exception so
    untouched paths degrade to a plain UNAVAILABLE error; front-ends
    that can sever the transport (HTTP) special-case it."""

    def __init__(self, msg: str = "connection dropped (chaos)"):
        super().__init__(msg, status="UNAVAILABLE")


class ChaosConfig:
    def __init__(self, latency_ms: float = 0.0, error_rate: float = 0.0,
                 drop_rate: float = 0.0, hang_ms: float = 0.0,
                 abandon_rate: float = 0.0,
                 abandon_after_ms: float = 0.0,
                 seed: Optional[int] = None,
                 models: Optional[set] = None,
                 replica: Optional[str] = None,
                 device: Optional[int] = None):
        self.latency_ms = max(float(latency_ms), 0.0)
        self.error_rate = min(max(float(error_rate), 0.0), 1.0)
        self.drop_rate = min(max(float(drop_rate), 0.0), 1.0)
        self.hang_ms = max(float(hang_ms), 0.0)
        self.abandon_rate = min(max(float(abandon_rate), 0.0), 1.0)
        self.abandon_after_ms = max(float(abandon_after_ms), 0.0)
        self.seed = seed
        self.models = set(models) if models else None
        # "model:index" retargets this config at one replica's
        # execution path (see module docstring); None = request level.
        self.replica = str(replica) if replica else None
        # Device id retargets at any execution whose device set holds
        # this chip — one chip of a mesh slice; None = no device gate.
        self.device = int(device) if device is not None else None

    @property
    def enabled(self) -> bool:
        return bool(self.latency_ms or self.error_rate or self.drop_rate
                    or self.hang_ms or self.abandon_rate)

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosConfig":
        """Parse ``"latency_ms=50,error_rate=0.1,drop_rate=0.01,
        hang_ms=0,seed=7,models=a+b,replica=simple:1"``; unknown keys
        fail loudly."""
        kwargs: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError("chaos spec entry '%s' is not key=value"
                                 % part)
            key = key.strip()
            value = value.strip()
            if key in ("latency_ms", "error_rate", "drop_rate",
                       "hang_ms", "abandon_rate", "abandon_after_ms"):
                kwargs[key] = float(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            elif key == "models":
                kwargs["models"] = {m for m in value.split("+") if m}
            elif key == "replica":
                if ":" not in value:
                    raise ValueError(
                        "chaos replica target '%s' is not model:index"
                        % value)
                kwargs["replica"] = value
            elif key == "device":
                kwargs["device"] = int(value)
            else:
                raise ValueError("unknown chaos spec key '%s'" % key)
        return cls(**kwargs)

    def describe(self) -> str:
        parts = []
        if self.latency_ms:
            parts.append("+%gms latency" % self.latency_ms)
        if self.error_rate:
            parts.append("%.0f%% errors" % (self.error_rate * 100))
        if self.drop_rate:
            parts.append("%.0f%% drops" % (self.drop_rate * 100))
        if self.hang_ms:
            parts.append("%gms hangs" % self.hang_ms)
        if self.abandon_rate:
            parts.append("%.0f%% abandons" % (self.abandon_rate * 100))
        described = ", ".join(parts) if parts else "disabled"
        if self.replica and parts:
            described += " @ replica %s" % self.replica
        if self.device is not None and parts:
            described += " @ device %d" % self.device
        return described


class _ChaosState:
    def __init__(self):
        self.lock = threading.Lock()
        self.config: Optional[ChaosConfig] = None
        # Scoped configs: named injection targets for multi-core
        # processes (an in-process fleet). A core whose `chaos_scope`
        # matches gets the scope's faults ON TOP of the global config —
        # this is how one replica of N can be degraded alone.
        self.scoped: dict = {}
        # Replica-targeted slot (configure_replica): an independent
        # layer for scenario-driven single-replica faults, so a
        # DegradeOneScenario in replica mode compounds with — instead
        # of clobbering — an operator's global --chaos config.
        self.replica_config: Optional[ChaosConfig] = None
        self.rng = random.Random()
        self.injected_errors = 0
        self.injected_drops = 0
        self.delayed_requests = 0
        self.injected_hangs = 0
        self.abandoned_requests = 0
        self._env_checked = False


_state = _ChaosState()


def configure(config: Optional[ChaosConfig]) -> None:
    """Install (or, with None, clear) the process-wide chaos config and
    reset the injection counters (scoped configs are cleared too)."""
    with _state.lock:
        _state.config = config if config is not None and config.enabled \
            else None
        _state.scoped = {}
        _state.replica_config = None
        _state.rng = random.Random(
            config.seed if config is not None else None)
        _state.injected_errors = 0
        _state.injected_drops = 0
        _state.delayed_requests = 0
        _state.injected_hangs = 0
        _state.abandoned_requests = 0
        _state._env_checked = True  # explicit config beats the env


def configure_scope(scope: str, config: Optional[ChaosConfig]) -> None:
    """Install (or, with None, clear) a NAMED chaos config. Only cores
    whose ``chaos_scope`` equals ``scope`` evaluate it — the tool for
    degrading one replica of an in-process fleet. Counters are shared
    with the global config and are NOT reset here (a scenario flips
    scopes mid-run; resetting would lose the run's totals)."""
    with _state.lock:
        if config is not None and config.enabled:
            _state.scoped[scope] = config
        else:
            _state.scoped.pop(scope, None)
        _state._env_checked = True


def configure_replica(config: Optional[ChaosConfig]) -> None:
    """Install (or, with None, clear) the replica-targeted chaos slot
    (``config.replica`` must name a ``model:index``, or
    ``config.device`` a chip id). Independent of the global config and
    the scoped configs — a replica-mode DegradeOneScenario stages
    faults here so it compounds with an operator's baseline ``--chaos``
    instead of replacing it. Counters are shared and NOT reset
    (scenarios flip stages mid-run)."""
    with _state.lock:
        _state.replica_config = (
            config if config is not None and config.enabled
            and (config.replica or config.device is not None) else None)
        _state._env_checked = True


def configure_from_spec(spec: str) -> ChaosConfig:
    config = ChaosConfig.from_spec(spec)
    configure(config)
    return config


def _load_env_config() -> None:
    """One-shot CLIENT_TPU_CHAOS pickup, done lazily at the first
    inject() so standalone servers get chaos without code changes."""
    with _state.lock:
        if _state._env_checked:
            return
        _state._env_checked = True
        spec = os.environ.get(ENV_VAR, "")
    if spec:
        configure_from_spec(spec)
        with _state.lock:  # keep env-sourced config re-checkable
            _state._env_checked = True


def stats() -> dict:
    with _state.lock:
        return {
            "injected_errors": _state.injected_errors,
            "injected_drops": _state.injected_drops,
            "delayed_requests": _state.delayed_requests,
            "injected_hangs": _state.injected_hangs,
            "abandoned_requests": _state.abandoned_requests,
        }


def inject(model_name: str = "", scope: Optional[str] = None,
           replica_id: Optional[str] = None, cancel=None,
           device_ids=None) -> None:
    """Request-path hook: sleep/raise per the active config(s). No-op
    (one lock-free attribute read) when chaos is off. ``scope`` names
    the calling core; a matching scoped config applies on top of the
    global one (fault kinds compound: delays add, the first raising
    kind wins). ``replica_id`` ("model:index") names the replica whose
    device queue is executing and ``device_ids`` the chip set that
    execution occupies (one id per-device, every slice member when the
    replica is a mesh slice): replica- and device-targeted configs
    fire only here — a device config for any chip in ``device_ids``,
    so one sick chip fails its whole slice; untargeted configs fire
    only at the request-level inject (``replica_id=None``) — one
    fault, one layer, never both. ``cancel`` is the request's
    CancelToken when the caller has one: abandon_rate faults fire by
    cancelling it after abandon_after_ms (a timer thread — the
    walked-away client), and are inert when cancellation is off (no
    token, no fault)."""
    if not _state._env_checked:
        _load_env_config()
    configs = []
    if _state.config is not None:
        configs.append(_state.config)
    if _state.replica_config is not None:
        configs.append(_state.replica_config)
    if scope is not None and _state.scoped:
        scoped = _state.scoped.get(scope)
        if scoped is not None:
            configs.append(scoped)
    if not configs:
        return
    delay_ms = 0.0
    hang_ms = 0.0
    drop = False
    error = None
    abandon_after_ms = None
    with _state.lock:
        for config in configs:
            if config.models is not None \
                    and model_name not in config.models:
                continue
            targeted = config.replica is not None \
                or config.device is not None
            if targeted != (replica_id is not None):
                continue  # wrong layer for this config
            if config.replica is not None \
                    and config.replica != replica_id:
                continue  # targeted at a sibling replica
            if config.device is not None and (
                    device_ids is None
                    or config.device not in device_ids):
                continue  # targeted at a chip this execution skips
            if config is not _state.config \
                    and config is not _state.replica_config \
                    and config is not _state.scoped.get(scope):
                continue  # reconfigured mid-flight
            roll = _state.rng.random()
            delay_ms += config.latency_ms
            hang_ms = max(hang_ms, config.hang_ms)
            if roll < config.drop_rate:
                drop = True
            elif roll < config.drop_rate + config.error_rate:
                error = config.error_rate
            # Independent roll, drawn ONLY when the fault is configured
            # so legacy specs keep their exact rng sequence.
            if config.abandon_rate and cancel is not None \
                    and _state.rng.random() < config.abandon_rate:
                abandon_after_ms = config.abandon_after_ms
        if delay_ms:
            _state.delayed_requests += 1
        if hang_ms:
            _state.injected_hangs += 1
        if drop:
            _state.injected_drops += 1
        elif error is not None:
            _state.injected_errors += 1
        if abandon_after_ms is not None:
            _state.abandoned_requests += 1
    if abandon_after_ms is not None:
        if abandon_after_ms <= 0:
            cancel.cancel("abandoned")
        else:
            timer = threading.Timer(abandon_after_ms / 1000.0,
                                    cancel.cancel, args=("abandoned",))
            timer.daemon = True
            timer.start()
    if delay_ms:
        time.sleep(delay_ms / 1000.0)
    if hang_ms:
        # Deterministic stall (no roll): the watchdog-catchable hang.
        time.sleep(hang_ms / 1000.0)
    if drop:
        raise ChaosDropError()
    if error is not None:
        # A tiny Retry-After: honest for a transient injected fault,
        # and small enough that retrying clients in the chaos smokes
        # keep their pressure up instead of pacing on a 1s floor.
        raise status_map.retryable_error(
            "injected fault (chaos error_rate=%g)" % error,
            retry_after_s=0.01)


class OverloadScenario:
    """Staged burst-arrival injection against ONE model: after
    ``burst_after_s`` a pool of ``workers`` closed-loop threads floods
    ``submit_fn`` (one call = one request; it may raise — rejects ARE
    the point) for ``burst_duration_s``, with seeded-jitter pacing so
    a run is reproducible. The saturation half of the CI overload
    gate: the burst drives a bounded queue to its max_queue_size while
    foreground traffic's QoS is measured.

    Spec string (perf ``--overload``), comma-separated key=value:
    ``rate=500,after_s=1,duration_s=3,workers=8,seed=11`` — rate is
    target submissions/sec across all workers (0 = as fast as the
    closed loops can go). Timings are relative to :meth:`start`.

    **Diurnal/trace mode**: ``trace=50:2+500:3+50:2,repeat=2`` replays
    a repeating multi-stage Poisson schedule — each ``rate:duration_s``
    stage paces arrivals at that rate for that long (rate 0 = idle
    stage), the whole schedule ``repeat`` times. This is the 10x load
    swing the autoscale bench replays; ``rate``/``duration_s`` are
    ignored while a trace is set (``after_s`` still delays the start).
    """

    def __init__(self, submit_fn, rate: float = 0.0,
                 burst_after_s: float = 0.0,
                 burst_duration_s: float = 3.0,
                 workers: int = 8, seed: int = 11,
                 trace=None, repeat: int = 1):
        self.submit_fn = submit_fn
        self.rate = max(float(rate), 0.0)
        self.burst_after_s = max(float(burst_after_s), 0.0)
        self.burst_duration_s = max(float(burst_duration_s), 0.0)
        self.workers = max(int(workers), 1)
        self.seed = seed
        # [(rate, duration_s), ...] or None — see class docstring.
        self.trace = [(max(float(r), 0.0), max(float(d), 0.0))
                      for r, d in (trace or [])] or None
        self.repeat = max(int(repeat), 1)
        self.submitted = 0
        self.rejected = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        self.started = threading.Event()
        self.finished = threading.Event()

    @classmethod
    def parse_spec(cls, spec: str) -> dict:
        """``"rate=500,after_s=1,duration_s=3,workers=8,seed=11"`` ->
        constructor kwargs; unknown keys fail loudly."""
        kwargs: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(
                    "overload spec entry '%s' is not key=value" % part)
            key = key.strip()
            if key == "rate":
                kwargs["rate"] = float(value)
            elif key == "after_s":
                kwargs["burst_after_s"] = float(value)
            elif key == "duration_s":
                kwargs["burst_duration_s"] = float(value)
            elif key == "workers":
                kwargs["workers"] = int(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            elif key == "trace":
                stages = []
                for stage in value.split("+"):
                    rate_s, sep2, dur_s = stage.partition(":")
                    if not sep2:
                        raise ValueError(
                            "overload trace stage '%s' is not "
                            "rate:duration_s" % stage)
                    stages.append((float(rate_s), float(dur_s)))
                kwargs["trace"] = stages
            elif key == "repeat":
                kwargs["repeat"] = int(value)
            else:
                raise ValueError("unknown overload spec key '%s'" % key)
        return kwargs

    def start(self) -> "OverloadScenario":
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True,
                             name="chaos-overload-%d" % i)
            for i in range(self.workers)
        ]
        for thread in self._threads:
            thread.start()
        return self

    def _run(self, index: int) -> None:
        # Per-worker seeded rng: pacing jitter is reproducible AND
        # uncorrelated across workers (one shared rng under a lock
        # would serialize the burst it exists to create).
        rng = random.Random(self.seed * 1_000_003 + index)
        if self._stop.wait(self.burst_after_s):
            return
        self.started.set()
        if self.trace is not None:
            # Diurnal replay: each (rate, duration) stage in order,
            # the whole schedule `repeat` times.
            for _cycle in range(self.repeat):
                for rate, duration_s in self.trace:
                    self._stage(rng, rate, duration_s)
                    if self._stop.is_set():
                        return
        else:
            self._stage(rng, self.rate, self.burst_duration_s)
            if self._stop.is_set():
                return
        self.finished.set()

    def _stage(self, rng, rate: float, duration_s: float) -> None:
        """One constant-rate Poisson stage (rate 0 in trace mode =
        idle: wait the stage out without submitting)."""
        deadline = time.monotonic() + duration_s
        per_worker_rate = rate / self.workers if rate else 0.0
        if rate == 0.0 and self.trace is not None:
            self._stop.wait(duration_s)
            return
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                self.submit_fn()
                with self._lock:
                    self.submitted += 1
            except Exception:  # noqa: BLE001 — rejects are the point
                with self._lock:
                    self.submitted += 1
                    self.rejected += 1
            if per_worker_rate > 0:
                # Exponential inter-arrival: a Poisson burst, the
                # arrival process queueing theory (and the adaptive
                # batcher window) assumes, not a metronome.
                pause = rng.expovariate(per_worker_rate)
                if self._stop.wait(min(pause, 1.0)):
                    return
        return

    def stop(self) -> None:
        """Cancel the burst (or wait out stragglers) and join."""
        self._stop.set()
        for thread in self._threads:
            thread.join(timeout=5)

    def stats(self) -> dict:
        with self._lock:
            return {"submitted": self.submitted,
                    "rejected": self.rejected}


class DegradeOneScenario:
    """Staged degradation of ONE fault domain: after
    ``latency_after_s`` the victim gets a latency spike (the brown-out
    hedging is built for), after ``kill_after_s`` the victim is
    hard-killed (the outage failover/ejection is built for), and —
    replica mode only — after ``heal_after_s`` the fault clears so the
    supervisor can canary-probe and readmit. Any stage may be disabled
    (None).

    Two victim addressing modes:

    * **Fleet mode** (``scopes`` + ``kill_fns``): the victim is one
      in-process server core named by its chaos scope; kill invokes
      the matching callback (PR-4 endpoint failover).
    * **Replica mode** (``replica="model:index"``): the victim is one
      replica of an instance-group model; the spike/kill stages
      install replica-targeted ChaosConfigs (kill = ``error_rate=1``,
      or a deterministic ``hang_ms`` stall with ``kill_kind=hang`` so
      the execution watchdog — not the breaker — must catch it). This
      is the intra-host blast-radius scenario the replica chaos smoke
      gates on: siblings and the front-of-house path stay clean.

    Spec string (perf ``--degrade-one``), comma-separated key=value:
    ``latency_ms=200,latency_after_s=1,kill_after_s=3,victim=1`` or
    ``replica=simple:2,kill_after_s=2,heal_after_s=5``.
    Timings are relative to :meth:`start`.
    """

    def __init__(self, scopes=(), kill_fns=(), latency_ms: float = 0.0,
                 latency_after_s: Optional[float] = None,
                 kill_after_s: Optional[float] = None,
                 victim: int = -1,
                 replica: Optional[str] = None,
                 kill_kind: str = "error",
                 hang_ms: float = 10_000.0,
                 heal_after_s: Optional[float] = None):
        self.replica = str(replica) if replica else None
        if self.replica is None:
            if len(scopes) != len(kill_fns):
                raise ValueError("one kill_fn per scope required")
            if not scopes:
                raise ValueError(
                    "DegradeOneScenario needs at least one scope "
                    "(or a replica= target)")
        self.scopes = list(scopes)
        self.kill_fns = list(kill_fns)
        self.latency_ms = float(latency_ms)
        self.latency_after_s = latency_after_s
        self.kill_after_s = kill_after_s
        self.heal_after_s = heal_after_s
        self.victim = victim % len(scopes) if scopes else 0
        if kill_kind not in ("error", "hang"):
            raise ValueError("kill_kind must be 'error' or 'hang'")
        self.kill_kind = kill_kind
        self.hang_ms = float(hang_ms)
        self.killed = threading.Event()
        self.spiked = threading.Event()
        self.healed = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def parse_spec(cls, spec: str) -> dict:
        """``"latency_ms=200,latency_after_s=1,kill_after_s=3,
        victim=1"`` (fleet) or ``"replica=simple:2,kill_after_s=2,
        kill_kind=hang,heal_after_s=5"`` (replica) -> constructor
        kwargs; unknown keys fail loudly."""
        kwargs: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(
                    "degrade-one spec entry '%s' is not key=value" % part)
            key = key.strip()
            if key in ("latency_ms", "latency_after_s", "kill_after_s",
                       "heal_after_s", "hang_ms"):
                kwargs[key] = float(value)
            elif key == "victim":
                kwargs["victim"] = int(value)
            elif key == "replica":
                if ":" not in value:
                    raise ValueError(
                        "degrade-one replica target '%s' is not "
                        "model:index" % value)
                kwargs["replica"] = value
            elif key == "kill_kind":
                kwargs["kill_kind"] = value.strip().lower()
            else:
                raise ValueError(
                    "unknown degrade-one spec key '%s'" % key)
        return kwargs

    def start(self) -> "DegradeOneScenario":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-degrade-one")
        self._thread.start()
        return self

    def _run(self) -> None:
        t0 = time.monotonic()

        def wait_until(offset_s: float) -> bool:
            remaining = t0 + offset_s - time.monotonic()
            if remaining > 0 and self._stop.wait(remaining):
                return False
            return not self._stop.is_set()

        if self.replica is not None:
            self._run_replica(wait_until)
            return
        scope = self.scopes[self.victim]
        if self.latency_after_s is not None and self.latency_ms > 0:
            if not wait_until(self.latency_after_s):
                return
            configure_scope(scope, ChaosConfig(latency_ms=self.latency_ms))
            self.spiked.set()
        if self.kill_after_s is not None:
            if not wait_until(self.kill_after_s):
                return
            # the spike ends when the process does — clear it so the
            # shared rng isn't consulted for a dead replica
            configure_scope(scope, None)
            try:
                self.kill_fns[self.victim]()
            finally:
                self.killed.set()

    def _run_replica(self, wait_until) -> None:
        """Replica-mode stages: spike -> kill -> heal, each installed
        in the dedicated replica-targeted chaos slot
        (:func:`configure_replica`) so the scenario compounds with an
        operator's global --chaos config instead of replacing it. Each
        stage supersedes the previous one; faults fire only at the
        victim replica's execution path (chaos.inject with replica_id;
        siblings never roll)."""
        target = self.replica
        if self.latency_after_s is not None and self.latency_ms > 0:
            if not wait_until(self.latency_after_s):
                return
            configure_replica(ChaosConfig(latency_ms=self.latency_ms,
                                          replica=target))
            self.spiked.set()
        if self.kill_after_s is not None:
            if not wait_until(self.kill_after_s):
                return
            if self.kill_kind == "hang":
                configure_replica(ChaosConfig(hang_ms=self.hang_ms,
                                              replica=target))
            else:
                configure_replica(ChaosConfig(error_rate=1.0,
                                              replica=target))
            self.killed.set()
        if self.heal_after_s is not None:
            if not wait_until(self.heal_after_s):
                return
            configure_replica(None)
            self.healed.set()

    def stop(self) -> None:
        """Cancel pending stages and clear the victim's faults (a
        fleet-mode kill already fired is not undone)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.replica is not None:
            configure_replica(None)
        else:
            configure_scope(self.scopes[self.victim], None)
