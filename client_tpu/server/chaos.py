"""Fault injection for the server request path.

Chaos is configured per process (``configure`` / the CLIENT_TPU_CHAOS
environment variable) and evaluated by :func:`inject`, which the server
core calls once per inference request. Three fault kinds:

* ``latency_ms`` — added service latency (sleep before execution).
* ``error_rate`` — fraction of requests failed with UNAVAILABLE, the
  shape a crashing backend or evicted pod produces.
* ``drop_rate`` — fraction of requests failed as *connection drops*:
  the HTTP front-end closes the TCP transport mid-request (the client
  sees a reset, not an error body); gRPC surfaces UNAVAILABLE with a
  drop marker. Raised as :class:`ChaosDropError` so front-ends can
  distinguish a drop from an ordinary injected error.

Spec strings (``--chaos`` / CLIENT_TPU_CHAOS) are comma-separated
``key=value`` pairs, e.g. ``"latency_ms=50,error_rate=0.1,seed=7"``.
An optional ``models=a+b`` entry restricts injection to those models.

Everything is deterministic under ``seed`` so a chaos run is
reproducible — the property that turns "it degrades gracefully" into a
regression-gated measurement.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

from client_tpu.utils import InferenceServerException

ENV_VAR = "CLIENT_TPU_CHAOS"


class ChaosDropError(InferenceServerException):
    """An injected connection drop. Subclasses the server exception so
    untouched paths degrade to a plain UNAVAILABLE error; front-ends
    that can sever the transport (HTTP) special-case it."""

    def __init__(self, msg: str = "connection dropped (chaos)"):
        super().__init__(msg, status="UNAVAILABLE")


class ChaosConfig:
    def __init__(self, latency_ms: float = 0.0, error_rate: float = 0.0,
                 drop_rate: float = 0.0, seed: Optional[int] = None,
                 models: Optional[set] = None):
        self.latency_ms = max(float(latency_ms), 0.0)
        self.error_rate = min(max(float(error_rate), 0.0), 1.0)
        self.drop_rate = min(max(float(drop_rate), 0.0), 1.0)
        self.seed = seed
        self.models = set(models) if models else None

    @property
    def enabled(self) -> bool:
        return bool(self.latency_ms or self.error_rate or self.drop_rate)

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosConfig":
        """Parse ``"latency_ms=50,error_rate=0.1,drop_rate=0.01,
        seed=7,models=a+b"``; unknown keys fail loudly."""
        kwargs: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError("chaos spec entry '%s' is not key=value"
                                 % part)
            key = key.strip()
            value = value.strip()
            if key in ("latency_ms", "error_rate", "drop_rate"):
                kwargs[key] = float(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            elif key == "models":
                kwargs["models"] = {m for m in value.split("+") if m}
            else:
                raise ValueError("unknown chaos spec key '%s'" % key)
        return cls(**kwargs)

    def describe(self) -> str:
        parts = []
        if self.latency_ms:
            parts.append("+%gms latency" % self.latency_ms)
        if self.error_rate:
            parts.append("%.0f%% errors" % (self.error_rate * 100))
        if self.drop_rate:
            parts.append("%.0f%% drops" % (self.drop_rate * 100))
        return ", ".join(parts) if parts else "disabled"


class _ChaosState:
    def __init__(self):
        self.lock = threading.Lock()
        self.config: Optional[ChaosConfig] = None
        self.rng = random.Random()
        self.injected_errors = 0
        self.injected_drops = 0
        self.delayed_requests = 0
        self._env_checked = False


_state = _ChaosState()


def configure(config: Optional[ChaosConfig]) -> None:
    """Install (or, with None, clear) the process-wide chaos config and
    reset the injection counters."""
    with _state.lock:
        _state.config = config if config is not None and config.enabled \
            else None
        _state.rng = random.Random(
            config.seed if config is not None else None)
        _state.injected_errors = 0
        _state.injected_drops = 0
        _state.delayed_requests = 0
        _state._env_checked = True  # explicit config beats the env


def configure_from_spec(spec: str) -> ChaosConfig:
    config = ChaosConfig.from_spec(spec)
    configure(config)
    return config


def _load_env_config() -> None:
    """One-shot CLIENT_TPU_CHAOS pickup, done lazily at the first
    inject() so standalone servers get chaos without code changes."""
    with _state.lock:
        if _state._env_checked:
            return
        _state._env_checked = True
        spec = os.environ.get(ENV_VAR, "")
    if spec:
        configure_from_spec(spec)
        with _state.lock:  # keep env-sourced config re-checkable
            _state._env_checked = True


def stats() -> dict:
    with _state.lock:
        return {
            "injected_errors": _state.injected_errors,
            "injected_drops": _state.injected_drops,
            "delayed_requests": _state.delayed_requests,
        }


def inject(model_name: str = "") -> None:
    """Request-path hook: sleep/raise per the active config. No-op
    (one lock-free attribute read) when chaos is off."""
    if not _state._env_checked:
        _load_env_config()
    config = _state.config
    if config is None:
        return
    if config.models is not None and model_name not in config.models:
        return
    with _state.lock:
        if _state.config is not config:  # reconfigured mid-flight
            return
        roll = _state.rng.random()
        delay_ms = config.latency_ms
        drop = roll < config.drop_rate
        error = not drop and roll < config.drop_rate + config.error_rate
        if delay_ms:
            _state.delayed_requests += 1
        if drop:
            _state.injected_drops += 1
        elif error:
            _state.injected_errors += 1
    if delay_ms:
        time.sleep(delay_ms / 1000.0)
    if drop:
        raise ChaosDropError()
    if error:
        raise InferenceServerException(
            "injected fault (chaos error_rate=%g)" % config.error_rate,
            status="UNAVAILABLE")
