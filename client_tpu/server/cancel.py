"""Request-lifecycle cancellation: one token, every stage, all resources.

PR-2 gave requests a deadline but only honored it *before* dispatch:
once a request left the front of the batcher queue the server computed
to completion whether or not anybody was still listening. Under hedged
(PR-4) and retried traffic, and abandoned LLM streams (PR-13), that is
the "wasted work amplification" failure mode from Dean & Barroso's
*The Tail at Scale* — device time spent producing responses nobody
reads.

This module is the one signal that threads through every layer:

``CancelToken``
    Minted at admission (``core.infer`` / ``core.stream_infer``),
    carries the request's absolute deadline and a cancel flag.
    *Sources* (HTTP transport close, embed socket EOF, gRPC context
    callbacks, the ``/v2/cancel/<id>`` route, hedging losers, chaos
    ``abandon_rate``) call :meth:`CancelToken.cancel`. *Sinks* (the
    batcher, the LLM scheduler, ensembles, cache followers, sequence
    slots) either poll :meth:`raise_if_cancelled` at stage boundaries
    or register a wakeup via :meth:`on_cancel` — every ``on_cancel``
    must be paired with :meth:`remove_callback` in a ``finally``
    (tpulint's resource-pairing checker enforces this, same as
    acquire/release).

``CancelRegistry``
    Bounded request-id -> token map powering explicit wire
    cancellation (``core.cancel_request``), plus the subsystem
    kill-switch: ``registry.enabled`` (env ``CLIENT_TPU_CANCEL=off``)
    disables token minting entirely so the paired-A/B overhead driver
    can price the hot-path cost of the always-on checks.

Cancellation raised by a token is an ``InferenceServerException`` with
status ``CANCELLED`` (or ``DEADLINE_EXCEEDED`` when the deadline — not
an explicit signal — fired after dispatch) carrying a ``cancel_stage``
attribute naming the stage boundary where the signal landed; the core
turns that into ``tpu_request_cancelled_total{model,stage}`` and the
``cancelled`` terminal span attr.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from client_tpu.utils import InferenceServerException

#: Canonical cancellation reasons. Free-form strings are accepted too;
#: these exist so sources agree on spelling (the reason lands in the
#: error message, the flight recorder, and the ``cancelled`` span attr).
REASON_CLIENT_DISCONNECT = "client_disconnect"
REASON_WIRE_CANCEL = "wire_cancel"
REASON_DEADLINE = "deadline"
REASON_HEDGE_LOSER = "hedge_loser"
REASON_RETRY_ABANDONED = "retry_abandoned"
REASON_ABANDONED = "abandoned"

_ENV_FLAG = "CLIENT_TPU_CANCEL"
_OFF_VALUES = ("off", "0", "false", "no")


def cancelled_error(message: str, stage: str,
                    status: str = "CANCELLED") -> InferenceServerException:
    """A CANCELLED (or post-dispatch DEADLINE_EXCEEDED) error stamped
    with the stage boundary where the signal landed."""
    error = InferenceServerException(message, status=status)
    error.cancel_stage = stage
    return error


def deadline_from_timeout_us(timeout_us,
                             now_ns: Optional[int] = None) -> Optional[int]:
    """Absolute monotonic deadline from the PR-2 ``timeout`` request
    parameter (microseconds), or None when absent/invalid. The same
    parameter the batcher's queue policy reads — the token simply
    carries it past dispatch."""
    try:
        timeout_us = int(timeout_us)
    except (TypeError, ValueError):
        return None
    if timeout_us <= 0:
        return None
    if now_ns is None:
        now_ns = time.monotonic_ns()
    return now_ns + timeout_us * 1000


class CancelToken:
    """Per-request cancel flag + absolute deadline, observed at every
    stage boundary.

    Thread-safe. ``cancel()`` is idempotent; callbacks registered via
    ``on_cancel`` fire exactly once (immediately, if registration
    happens after cancellation) and are invoked outside the token lock
    so they may take subsystem locks (batcher CV, scheduler CV).
    """

    __slots__ = ("request_id", "deadline_ns", "reason", "stage",
                 "_cancelled", "_lock", "_callbacks", "_next_handle")

    def __init__(self, deadline_ns: Optional[int] = None,
                 request_id: Optional[str] = None):
        self.request_id = request_id
        self.deadline_ns = deadline_ns
        self.reason: Optional[str] = None
        #: Stage boundary where the signal landed (first raise wins);
        #: the core copies it into the terminal span attr.
        self.stage: Optional[str] = None
        self._cancelled = False
        self._lock = threading.Lock()
        self._callbacks: Dict[int, Callable[[], None]] = {}
        self._next_handle = 0

    # -- source side ---------------------------------------------------

    def cancel(self, reason: str = REASON_WIRE_CANCEL) -> bool:
        """Flip the flag and fire registered wakeups. Returns True if
        this call performed the transition (False when already
        cancelled — late losers and double disconnects are no-ops)."""
        with self._lock:
            if self._cancelled:
                return False
            self._cancelled = True
            self.reason = reason
            callbacks = list(self._callbacks.values())
            self._callbacks.clear()
        for fn in callbacks:
            try:
                fn()
            except Exception:
                pass  # a sink's wakeup must never mask the signal
        return True

    # -- sink side -----------------------------------------------------

    def cancelled(self) -> bool:
        return self._cancelled

    def expired(self, now_ns: Optional[int] = None) -> bool:
        if self.deadline_ns is None:
            return False
        return (now_ns or time.monotonic_ns()) >= self.deadline_ns

    def cancelled_or_expired(self, now_ns: Optional[int] = None) -> bool:
        return self._cancelled or self.expired(now_ns)

    def remaining_us(self, now_ns: Optional[int] = None) -> Optional[int]:
        """Microseconds of deadline budget left (floored at 0), or
        None when the request carries no deadline. Ensembles use this
        to hand each composing stage the *remaining* budget instead of
        the full original timeout."""
        if self.deadline_ns is None:
            return None
        remaining = self.deadline_ns - (now_ns or time.monotonic_ns())
        return max(0, remaining // 1000)

    def raise_if_cancelled(self, stage: str,
                           now_ns: Optional[int] = None) -> None:
        """Stage-boundary check: raise CANCELLED when a source fired,
        DEADLINE_EXCEEDED when only the deadline lapsed (deadline
        expiry *after* dispatch — PR-2 checked it only before)."""
        if self._cancelled:
            if self.stage is None:
                self.stage = stage
            raise cancelled_error(
                "request cancelled (%s) at stage %r"
                % (self.reason or "cancelled", stage), stage)
        if self.expired(now_ns):
            if self.stage is None:
                self.stage = stage
            raise cancelled_error(
                "deadline exceeded after dispatch at stage %r" % stage,
                stage, status="DEADLINE_EXCEEDED")

    def on_cancel(self, fn: Callable[[], None]) -> int:
        """Register a wakeup fired on cancellation; returns a handle
        for :meth:`remove_callback`. Pair every registration with a
        ``remove_callback`` in a ``finally`` — tokens outlive the
        stage that registered, and a stale wakeup poking a recycled
        pending is a use-after-free in spirit. If the token is already
        cancelled the wakeup fires immediately (the handle is still
        returned and still valid to remove)."""
        fire = False
        with self._lock:
            self._next_handle += 1
            handle = self._next_handle
            if self._cancelled:
                fire = True
            else:
                self._callbacks[handle] = fn
        if fire:
            try:
                fn()
            except Exception:
                pass
        return handle

    def remove_callback(self, handle: int) -> None:
        with self._lock:
            self._callbacks.pop(handle, None)


class CancelRegistry:
    """Mints tokens and tracks in-flight ones by request id so
    explicit wire cancellation (`POST /v2/cancel/<id>`, hedge-loser
    cancels) can find them. Bounded like the flight recorder's
    in-flight table: beyond MAX_TRACKED the oldest entry is evicted —
    an evicted request simply can't be wire-cancelled any more, it
    still honors disconnect/deadline signals via its token."""

    MAX_TRACKED = 4096

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get(
                _ENV_FLAG, "on").strip().lower() not in _OFF_VALUES
        #: Kill switch: when False the core mints no tokens and every
        #: stage check short-circuits on ``cancel is None``. The
        #: paired-A/B overhead driver flips this per round.
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._tokens: "OrderedDict[str, CancelToken]" = OrderedDict()
        self.cancelled_by_id = 0
        self.unknown_id_cancels = 0

    def mint(self, request_id: Optional[str] = None,
             timeout_us=None) -> CancelToken:
        token = CancelToken(
            deadline_ns=deadline_from_timeout_us(timeout_us),
            request_id=request_id or None)
        return token

    def track(self, token: CancelToken) -> None:
        """Index the token by request id (no-op for id-less requests —
        in-process callers hold the token object directly)."""
        if not token.request_id:
            return
        with self._lock:
            self._tokens[token.request_id] = token
            self._tokens.move_to_end(token.request_id)
            while len(self._tokens) > self.MAX_TRACKED:
                self._tokens.popitem(last=False)

    def untrack(self, token: CancelToken) -> None:
        if not token.request_id:
            return
        with self._lock:
            existing = self._tokens.get(token.request_id)
            if existing is token:
                del self._tokens[token.request_id]

    def cancel(self, request_id: str,
               reason: str = REASON_WIRE_CANCEL) -> bool:
        """Explicit wire cancellation by request id. True if a tracked
        in-flight request was found (whether or not this call won the
        cancel race); False for unknown/already-finished ids."""
        with self._lock:
            token = self._tokens.get(request_id or "")
        if token is None:
            with self._lock:
                self.unknown_id_cancels += 1
            return False
        token.cancel(reason)
        with self._lock:
            self.cancelled_by_id += 1
        return True

    def inflight(self) -> int:
        with self._lock:
            return len(self._tokens)
