"""Content-addressed response cache with single-flight deduplication.

The server-side counterpart of Triton's local response cache (the
feature both perf parsers already read as ``response_cache.enable`` and
whose latency caveat the harness prints): a byte-budgeted LRU over
*encoded* ``ModelInferResponse`` protos, keyed by a content hash of the
wire request — model, version, every input tensor's name/dtype/shape/
bytes, the requested outputs (with their response-shaping parameters),
and the cache-relevant request parameters. Hits are served before the
request is even decoded: no input deserialization, no queue, no
batcher, no model execution, no output encoding.

Two deliberate departures from the Triton design:

* **Single-flight deduplication.** Concurrent identical misses
  coalesce: the first becomes the *leader* and executes normally;
  followers park on the leader's flight and are served its response
  (bounded by their own queue deadline, PR-2 semantics). A burst of N
  identical requests executes the model once, not N times — Clipper's
  prediction-cache observation applied at admission time.
* **Host-only entries.** Cached responses are already-serialized host
  bytes; the cache never pins device buffers, so HBM pressure is
  unaffected by cache sizing.

Bypass rules (the request never touches the cache):

* stateful sequence requests (``sequence_id`` — step results depend on
  scheduler state, not request content),
* decoupled/streaming models (zero-or-many responses have no single
  cacheable value),
* any input or requested output routed through a shared-memory region
  (region contents are not content-addressable from the wire request,
  and shm outputs need per-request side effects),
* failed executions (errors resolve the flight but are never
  inserted).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from client_tpu.protocol import inference_pb2 as pb

# 64 MiB default budget — ~200k cached `simple` responses (payload +
# per-entry overhead), or a few thousand BERT-sized ones; override per
# server via the cache_size knob (InferenceServerCore /
# app --cache-size / CLIENT_TPU_CACHE_SIZE).
DEFAULT_CACHE_BYTES = 64 << 20

# Request parameters that must NOT contribute to the content hash:
# QoS/transport knobs that do not change the response payload
# (`tenant` is admission identity — two tenants sending the same
# request share one cached response).
_UNCACHED_PARAMS = frozenset((
    "timeout",
    "priority",
    "tenant",
    "triton_enable_empty_final_response",
    "binary_data_output",
    # Per-request cancellation lifecycle — never response identity.
    "cancel_token",
))

# Any of these marks a correlated (stateful) request: bypass entirely.
_SEQUENCE_PARAMS = frozenset((
    "sequence_id", "sequence_start", "sequence_end",
))


def request_cache_key(model_name: str, model_version: str,
                      request: pb.ModelInferRequest) -> Optional[bytes]:
    """Content hash for one wire request, or ``None`` when the request
    is uncacheable (sequence params, shared-memory I/O).

    Hashed over the *wire form* (tensor bytes, not decoded arrays), so
    a hit never pays input deserialization. The same logical tensor
    sent via ``raw_input_contents`` vs typed ``contents`` hashes
    differently — that is only a missed dedup opportunity, never a
    correctness issue.
    """
    for key in request.parameters:
        if key in _SEQUENCE_PARAMS:
            return None
    h = hashlib.blake2b(digest_size=16)
    h.update(model_name.encode())
    h.update(b"\x00")
    h.update(model_version.encode())
    # Each tensor hashes as its serialized wire form (name, datatype,
    # shape, typed contents, parameters in one C-level call — the hit
    # path must stay a few microseconds). Within-process proto
    # serialization is stable; a nondeterministic map ordering would
    # only cost a spurious miss, never a wrong hit.
    for tensor in request.inputs:
        if "shared_memory_region" in tensor.parameters:
            return None
        h.update(b"\x01")
        h.update(tensor.SerializeToString())
    for raw in request.raw_input_contents:
        h.update(b"\x02")
        h.update(len(raw).to_bytes(8, "little"))
        h.update(raw)
    # Requested outputs shape the response (selection, classification
    # top-k), so they are part of the content address.
    for out in request.outputs:
        if "shared_memory_region" in out.parameters:
            return None
        h.update(b"\x03")
        h.update(out.SerializeToString())
    for key in sorted(request.parameters):
        if key in _UNCACHED_PARAMS:
            continue
        h.update(b"\x04")
        h.update(key.encode())
        h.update(request.parameters[key].SerializeToString())
    return h.digest()


class Flight:
    """One in-progress execution for a cache key. The leader resolves
    it with the encoded response (or marks it failed); followers wait
    on ``event`` bounded by their own queue deadline. ``priority`` is
    the leader's coerced class (0 = unclassed): a would-be follower of
    a strictly higher class must not coalesce behind a lower-class
    leader stuck at the back of the priority queue."""

    __slots__ = ("event", "response", "failed", "priority")

    def __init__(self, priority: int = 0):
        self.event = threading.Event()
        self.response: Optional[pb.ModelInferResponse] = None
        self.failed = False
        self.priority = priority


# Charged per entry on top of the serialized payload: key digest,
# OrderedDict slot, entry object, and bytes-object headers. Keeps the
# byte budget an honest bound on real host memory, not just payload.
ENTRY_OVERHEAD_BYTES = 128


class _Entry:
    __slots__ = ("model", "data", "nbytes")

    def __init__(self, model: str, data: bytes, nbytes: int):
        self.model = model
        self.data = data
        self.nbytes = nbytes


class _ModelCacheStats:
    """Per-model cache accounting the Prometheus families render."""

    __slots__ = ("entries", "bytes", "evictions", "coalesced",
                 "insert_skipped")

    def __init__(self):
        self.entries = 0
        self.bytes = 0
        self.evictions = 0
        # Followers served from a leader's flight (dedup wins).
        self.coalesced = 0
        # Responses larger than the whole budget (never cached).
        self.insert_skipped = 0


class ResponseCache:
    """Byte-budgeted LRU over encoded responses + the single-flight
    table. All operations are O(1) except ``invalidate_model`` (one
    scan, only on reload/unload). Thread-safe."""

    def __init__(self, max_bytes: int = DEFAULT_CACHE_BYTES):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._bytes = 0
        self._flights: Dict[bytes, Flight] = {}
        self._per_model: Dict[str, _ModelCacheStats] = {}

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- lookup / insert -------------------------------------------------

    def _model_stats(self, model: str) -> _ModelCacheStats:
        stats = self._per_model.get(model)
        if stats is None:
            stats = self._per_model[model] = _ModelCacheStats()
        return stats

    def lookup(self, key: bytes) -> Optional[bytes]:
        """LRU-touching lookup. Returns the stored *serialized*
        response (id cleared at insert) — callers parse a fresh proto
        and stamp the requester's own id."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            return entry.data

    def lookup_or_begin(self, key: bytes, priority: int = 0
                        ) -> Tuple[Optional[bytes], Optional[Flight], bool]:
        """(cached_bytes, flight, is_leader) in ONE atomic step. A
        separate lookup-miss followed by begin_flight would race: a
        leader that resolves and inserts between the two calls leaves
        the late thread leading a second redundant execution. Inserts
        happen BEFORE flight resolution on the leader path, so this
        atomic probe can never miss both. ``priority`` is stamped on a
        newly-led flight so higher-class arrivals can decline to
        coalesce behind it."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                return entry.data, None, False
            flight = self._flights.get(key)
            if flight is not None:
                return None, flight, False
            flight = Flight(priority)
            self._flights[key] = flight
            return None, flight, True

    def insert(self, model: str, key: bytes,
               response: pb.ModelInferResponse) -> bool:
        """Stores the serialized response (id cleared — the hit path
        stamps the requester's own id), evicting LRU entries until the
        byte budget holds. A response larger than the whole budget is
        never cached. Entries are host bytes only: the cache never
        pins device buffers or live proto graphs."""
        stored = pb.ModelInferResponse()
        stored.CopyFrom(response)
        stored.id = ""
        return self.insert_bytes(model, key, stored.SerializeToString())

    def insert_bytes(self, model: str, key: bytes, data: bytes) -> bool:
        """Stores an already-serialized payload under ``key`` —
        response protos from :meth:`insert`, or the tensor-codec bytes
        the ensemble dataflow caches per composing stage. Same budget,
        LRU order, and ``invalidate_model`` scope either way."""
        nbytes = len(data) + ENTRY_OVERHEAD_BYTES
        with self._lock:
            stats = self._model_stats(model)
            if nbytes > self.max_bytes:
                stats.insert_skipped += 1
                return False
            prior = self._entries.pop(key, None)
            if prior is not None:
                self._bytes -= prior.nbytes
                prior_stats = self._model_stats(prior.model)
                prior_stats.entries -= 1
                prior_stats.bytes -= prior.nbytes
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                victim_stats = self._model_stats(victim.model)
                victim_stats.entries -= 1
                victim_stats.bytes -= victim.nbytes
                victim_stats.evictions += 1
            self._entries[key] = _Entry(model, data, nbytes)
            self._bytes += nbytes
            stats.entries += 1
            stats.bytes += nbytes
            return True

    # -- single flight ---------------------------------------------------

    def begin_flight(self, key: bytes) -> Tuple[Flight, bool]:
        """(flight, is_leader). The first caller for a key leads and
        MUST later call resolve_flight or fail_flight (core does so in
        its success/except paths); everyone else follows."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                return flight, False
            flight = Flight()
            self._flights[key] = flight
            return flight, True

    def _close_flight(self, key: bytes, flight: Flight) -> None:
        with self._lock:
            if self._flights.get(key) is flight:
                del self._flights[key]

    def resolve_flight(self, key: bytes, flight: Flight,
                       response: pb.ModelInferResponse) -> None:
        flight.response = response
        self._close_flight(key, flight)
        flight.event.set()

    def fail_flight(self, key: bytes, flight: Flight) -> None:
        """Leader failed: wake followers with nothing — each falls back
        to its own execution (one failure must not fan out to the whole
        coalesced burst)."""
        flight.failed = True
        self._close_flight(key, flight)
        flight.event.set()

    def record_coalesced(self, model: str) -> None:
        with self._lock:
            self._model_stats(model).coalesced += 1

    # -- invalidation ----------------------------------------------------

    def invalidate_model(self, model: str) -> int:
        """Drops every entry for ``model`` (reload/unload: a new
        instance may produce different bytes for the same inputs)."""
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if e.model == model]
            for key in doomed:
                entry = self._entries.pop(key)
                self._bytes -= entry.nbytes
            stats = self._per_model.get(model)
            if stats is not None:
                stats.entries = 0
                stats.bytes = 0
            return len(doomed)

    # -- observability ---------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-model gauge/counter snapshot for /metrics: {model:
        {entries, bytes, evictions, coalesced, insert_skipped}}."""
        with self._lock:
            return {
                model: {
                    "entries": s.entries,
                    "bytes": s.bytes,
                    "evictions": s.evictions,
                    "coalesced": s.coalesced,
                    "insert_skipped": s.insert_skipped,
                }
                for model, s in self._per_model.items()
            }

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def total_entries(self) -> int:
        with self._lock:
            return len(self._entries)


def wants_response_cache(model) -> bool:
    """Per-model opt-in (``response_cache.enable`` in ModelConfig);
    decoupled models never cache (zero-or-many responses)."""
    return (
        bool(getattr(model, "response_cache", False))
        and not getattr(model, "decoupled", False)
    )


# -- stage-output tensor codec ------------------------------------------
#
# The ensemble dataflow caches *composing-stage* outputs (name ->
# ndarray dicts), not wire protos, so stage entries get their own
# compact framing: per tensor a length-prefixed name, numpy dtype
# string, shape, and the raw row-major bytes. Object-dtype tensors
# (BYTES outputs holding Python objects) are not byte-stable and make
# the whole dict uncacheable.

_CODEC_MAGIC = b"TCD1"


def encode_tensors(outputs: Dict[str, "object"]) -> Optional[bytes]:
    """Serializes a ``{name: ndarray}`` dict to host bytes, or ``None``
    when any tensor cannot be cached (object dtype). Device arrays are
    materialized here — call off the request path."""
    import numpy as np

    parts = [_CODEC_MAGIC, len(outputs).to_bytes(4, "little")]
    for name in sorted(outputs):
        array = np.asarray(outputs[name])
        if array.dtype.hasobject:
            return None
        if not array.flags.c_contiguous:
            array = np.ascontiguousarray(array)
        name_b = name.encode()
        dtype_b = array.dtype.str.encode()
        parts.append(len(name_b).to_bytes(2, "little"))
        parts.append(name_b)
        parts.append(len(dtype_b).to_bytes(2, "little"))
        parts.append(dtype_b)
        parts.append(len(array.shape).to_bytes(2, "little"))
        for dim in array.shape:
            parts.append(int(dim).to_bytes(8, "little"))
        raw = array.tobytes()
        parts.append(len(raw).to_bytes(8, "little"))
        parts.append(raw)
    return b"".join(parts)


def decode_tensors(data: bytes) -> Optional[Dict[str, "object"]]:
    """Inverse of :func:`encode_tensors`; returns ``None`` on framing
    mismatch (a corrupt or foreign entry is a cache miss, never an
    error)."""
    import numpy as np

    try:
        if data[:4] != _CODEC_MAGIC:
            return None
        view = memoryview(data)
        offset = 4
        count = int.from_bytes(view[offset:offset + 4], "little")
        offset += 4
        outputs: Dict[str, object] = {}
        for _ in range(count):
            name_len = int.from_bytes(view[offset:offset + 2], "little")
            offset += 2
            name = bytes(view[offset:offset + name_len]).decode()
            offset += name_len
            dtype_len = int.from_bytes(view[offset:offset + 2], "little")
            offset += 2
            dtype = np.dtype(bytes(view[offset:offset + dtype_len]).decode())
            offset += dtype_len
            ndim = int.from_bytes(view[offset:offset + 2], "little")
            offset += 2
            shape = []
            for _ in range(ndim):
                shape.append(int.from_bytes(view[offset:offset + 8],
                                            "little"))
                offset += 8
            nbytes = int.from_bytes(view[offset:offset + 8], "little")
            offset += 8
            raw = view[offset:offset + nbytes]
            offset += nbytes
            outputs[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
        if offset != len(data):
            return None
        return outputs
    except Exception:
        return None
