"""Flight recorder: tail-retained anomaly traces for every request.

PR 6's span tracer decides sampling at request START: at any realistic
``trace_rate`` the requests most worth inspecting — p99 outliers,
errors, shed/timeout victims — are captured only by luck, even though
the PR-10 histograms prove they happened. The flight recorder closes
that gap with *tail sampling* (the Dapper-lineage design): EVERY
request records its span tree into a cheap scratch
(:class:`client_tpu.server.tracing.RequestTrace`, created by the core
even when trace sampling said no), and the keep decision runs
*retroactively* at completion, when the request's fate is known:

* **error** — the request failed (any non-drop exception);
* **timeout** — its queue/single-flight deadline expired
  (``DEADLINE_EXCEEDED``);
* **shed** — admission control or overload shedding dropped it
  (``UNAVAILABLE``);
* **quota** — a tenant quota rejected it (``RESOURCE_EXHAUSTED``);
* **slow** — it succeeded but took longer than the model's latency
  threshold: the absolute ``flight_slow_us`` ModelConfig knob when
  set, else a p99 estimate derived live from the model's always-on
  ``tpu_request_duration_us`` histogram (refreshed at most once per
  second, and only once the histogram holds enough samples for the
  estimate to mean anything).

Kept traces land in a bounded per-model ring buffer (count AND byte
budget, oldest-overwritten) with their full span trees, request ids,
and error payloads — dumpable as JSON over ``GET /v2/debug/flight``
and flushable to chrome-trace files exactly like the PR-6 buffers, so
a p99 regression comes with the span trees that explain it. SLO burns
and replica breaker trips *stamp* the resident traces
(:meth:`FlightRecorder.mark_incident`): the ring entry then names the
incident it contributed to.

Cost discipline: the unkept path pays one monotonic subtraction and a
threshold compare; serialization (the expensive part) happens only for
kept traces, which are anomalies by construction. ``enabled=False``
(or ``CLIENT_TPU_FLIGHT=off``) short-circuits capture entirely — the
A/B arm the ``flight_overhead`` bench stage measures against, gated
<2% like the PR-10 telemetry layer.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from client_tpu.status_map import FLIGHT_KEEP_REASONS

# Per-model ring budgets (overridable per recorder): entries AND bytes
# both bound the ring; whichever is hit first evicts the oldest trace.
DEFAULT_MAX_ENTRIES = 256
DEFAULT_MAX_BYTES = 4 * 1024 * 1024

# Derived-p99 keep threshold: minimum histogram population before the
# estimate is trusted, and how often it is re-derived per model.
MIN_DERIVED_SAMPLES = 64
DERIVE_REFRESH_S = 1.0

# Incident stamps per record: a flapping replica (trip -> readmit ->
# trip) stamps the ring every cycle; past this cap the oldest stamp
# rolls off so a long-resident record stays bounded.
MAX_INCIDENT_STAMPS = 8

# In-flight registry hard cap: live requests are bounded by serving
# concurrency, but a leak (a caller that never completes) must not
# grow the registry without bound — past the cap new requests are
# simply not tracked (capture and keep still work).
MAX_TRACKED_INFLIGHT = 4096

# Ring-count cap: admission-stage rejects are keyed by the CLIENT-
# supplied model name (a quota reject fires before the name is
# validated), so a hostile client spraying names must not mint a ring
# per name — past the cap new names fold into one overflow ring (the
# qos.py tenant-cardinality pattern).
MAX_RINGS = 256
OVERFLOW_RING = "overflow"

# Client-controlled strings are clamped before a record (or in-flight
# entry) is built: request ids, model names, and error payloads (which
# embed both) arrive on the wire unauthenticated and unbounded — the
# gRPC front-end lifts message-size limits — and unclamped they would
# turn the retention rings into a memory DoS.
MAX_NAME_CHARS = 256
MAX_ID_CHARS = 128
MAX_ERROR_CHARS = 4096


class _Live:
    """One in-flight request's registry entry."""

    __slots__ = ("model", "request_id", "trace", "start_ns")

    def __init__(self, model: str, request_id: str, trace):
        self.model = model
        self.request_id = request_id
        self.trace = trace
        self.start_ns = trace.root.start_ns


class _ModelRing:
    """Bounded ring of kept flight records for one model."""

    __slots__ = ("entries", "bytes", "kept_total", "overwritten_total",
                 "oversized_total")

    def __init__(self):
        # deque of (record dict, nbytes); oldest at the left.
        self.entries: deque = deque()
        self.bytes = 0
        self.kept_total = 0
        self.overwritten_total = 0
        self.oversized_total = 0


class FlightRecorder:
    """Per-model tail-retention rings + the live in-flight registry
    the /v2/debug endpoint reads."""

    def __init__(self, enabled: Optional[bool] = None,
                 max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 telemetry=None):
        if enabled is None:
            import os

            enabled = os.environ.get(
                "CLIENT_TPU_FLIGHT", "").strip().lower() not in (
                    "off", "0", "false", "disabled")
        self.enabled = bool(enabled)
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        # The always-on histogram registry the derived-p99 threshold
        # reads (client_tpu.server.telemetry.ServerTelemetry); None
        # disables derived thresholds (absolute flight_slow_us only).
        self._telemetry = telemetry
        self._rings: Dict[str, _ModelRing] = {}
        self._lock = threading.Lock()
        self._live: Dict[int, _Live] = {}
        self._live_lock = threading.Lock()
        self._live_seq = 0
        # model -> (derived threshold us, monotonic stamp) — refreshed
        # lazily per observe, at most once per DERIVE_REFRESH_S.
        self._derived: Dict[str, tuple] = {}

    # -- in-flight registry ----------------------------------------------

    def track(self, model: str, request_id: str, trace) -> Optional[int]:
        """Registers a live request; returns the token ``untrack`` /
        ``observe`` take (None when the registry is at its cap)."""
        entry = _Live(str(model)[:MAX_NAME_CHARS],
                      str(request_id)[:MAX_ID_CHARS], trace)
        with self._live_lock:
            if len(self._live) >= MAX_TRACKED_INFLIGHT:
                return None
            self._live_seq += 1
            token = self._live_seq
            self._live[token] = entry
        return token

    def untrack(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._live_lock:
            self._live.pop(token, None)

    def in_flight(self) -> List[dict]:
        """Live requests with age and the stage they are in (the last
        COMPLETED span's name; spans are recorded at end time, so a
        request deep in execution shows the last boundary it crossed).
        Oldest first — the hung request an operator is hunting is at
        the top."""
        with self._live_lock:
            live = list(self._live.values())
        now_ns = time.monotonic_ns()
        out = []
        for entry in sorted(live, key=lambda e: e.start_ns):
            spans = entry.trace.snapshot()
            stage = spans[-1].name if len(spans) > 1 else "admitted"
            out.append({
                "model": entry.model,
                "request_id": entry.request_id,
                "trace_id": entry.trace.trace_id,
                "age_us": max(now_ns - entry.start_ns, 0) // 1000,
                "stage": stage,
            })
        return out

    # -- keep decision ----------------------------------------------------

    def slow_threshold_us(self, model, model_name: str) -> tuple:
        """(threshold_us, source) for the slow-keep decision: the
        model's absolute ``flight_slow_us`` when set, else a p99
        derived from the live request-duration histogram (0 = no slow
        keeps — not enough samples yet, or telemetry off)."""
        absolute = int(getattr(model, "flight_slow_us", 0) or 0)
        if absolute > 0:
            return absolute, "absolute"
        telemetry = self._telemetry
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return 0, "none"
        cached = self._derived.get(model_name)
        now = time.monotonic()
        if cached is not None and now - cached[1] < DERIVE_REFRESH_S:
            return cached[0], "derived_p99"
        from client_tpu.server.telemetry import estimate_quantile

        snap = telemetry.for_model(model_name).request.snapshot()
        if snap["count"] < MIN_DERIVED_SAMPLES:
            return 0, "none"
        threshold = int(estimate_quantile(snap["buckets"], 0.99))
        self._derived[model_name] = (threshold, now)
        return threshold, "derived_p99"

    def observe(self, model, model_name: str, request_id: str, trace,
                error: Optional[str] = None,
                status: Optional[str] = None,
                token: Optional[int] = None,
                allow_slow: bool = True) -> Optional[str]:
        """The retroactive keep decision for one completed request.
        ``trace`` must be finished (root closed). Returns the keep
        reason, or None when the request was unremarkable and the
        trace is discarded. Always untracks ``token``.
        ``allow_slow=False`` disables the slow keep (decoupled
        streams: their wall clock scales with response count by
        design, so only errors keep them)."""
        self.untrack(token)
        if not self.enabled:
            return None
        # Clamp the client-controlled strings BEFORE they key or fill
        # a record (see MAX_*_CHARS).
        model_name = str(model_name)[:MAX_NAME_CHARS]
        request_id = str(request_id)[:MAX_ID_CHARS]
        if error is not None:
            error = str(error)[:MAX_ERROR_CHARS]
        root = trace.root
        duration_us = max(root.end_ns - root.start_ns, 0) // 1000
        reason = None
        threshold_us = 0
        source = "none"
        if error is not None:
            reason = FLIGHT_KEEP_REASONS.get(status or "", "error")
        elif allow_slow:
            threshold_us, source = self.slow_threshold_us(model,
                                                          model_name)
            if threshold_us > 0 and duration_us >= threshold_us:
                reason = "slow"
        if reason is None:
            return None
        record = {
            "model": model_name,
            "request_id": request_id,
            "trace_id": trace.trace_id,
            "reason": reason,
            "status": status,
            "error": error,
            "duration_us": duration_us,
            "ts": time.time(),
            "incidents": [],
            "spans": [span.as_dict() for span in trace.snapshot()],
        }
        if reason == "slow":
            record["threshold_us"] = threshold_us
            record["threshold_source"] = source
        # Size the entry by its serialized form — the byte budget must
        # bound real memory, not a guess (the PR-5 cache lesson). Paid
        # only on keeps, which are anomalies by construction.
        nbytes = len(json.dumps(record, separators=(",", ":"),
                                default=str)) + 64
        with self._lock:
            ring = self._rings.get(model_name)
            if ring is None:
                if len(self._rings) >= MAX_RINGS:
                    model_name = OVERFLOW_RING
                ring = self._rings.setdefault(model_name, _ModelRing())
            if nbytes > self.max_bytes:
                # A single record exceeding the whole byte budget
                # would either evict all older evidence or, retained,
                # defeat the budget entirely (a memory-DoS lever with
                # client-fed payloads) — drop it and count the drop.
                ring.oversized_total += 1
                return reason
            ring.entries.append((record, nbytes))
            ring.bytes += nbytes
            ring.kept_total += 1
            self._evict_over_budget(ring)
        return reason

    def _evict_over_budget(self, ring: _ModelRing) -> None:
        """Oldest-out eviction down to the count/byte budgets (caller
        holds the lock). The NEWEST entry is never evicted — records
        larger than the whole budget were already dropped at insert
        (oversized_total), so the loop always terminates within
        budget."""
        while len(ring.entries) > 1 and (
                len(ring.entries) > self.max_entries
                or ring.bytes > self.max_bytes):
            _dropped, dropped_bytes = ring.entries.popleft()
            ring.bytes -= dropped_bytes
            ring.overwritten_total += 1

    # -- control-plane decisions -------------------------------------------

    def record_decision(self, model_name: str, label: str,
                        attrs: Optional[dict] = None) -> bool:
        """Appends a standalone control-plane record (autoscale
        resize, shed directive, scale-to-zero) to the model's ring.
        Unlike ``mark_incident`` — which stamps records already
        resident and is a no-op on an empty ring — a decision is its
        own evidence: the post-incident audit must show every scaling
        move even when no request trace happened to be kept around
        it. Returns False when disabled or the record was oversized."""
        if not self.enabled:
            return False
        model_name = str(model_name)[:MAX_NAME_CHARS]
        record = {
            "model": model_name,
            "reason": "decision",
            "decision": str(label)[:MAX_ERROR_CHARS],
            "attrs": attrs or {},
            "ts": time.time(),
            "incidents": [],
        }
        nbytes = len(json.dumps(record, separators=(",", ":"),
                                default=str)) + 64
        with self._lock:
            ring = self._rings.get(model_name)
            if ring is None:
                if len(self._rings) >= MAX_RINGS:
                    model_name = OVERFLOW_RING
                ring = self._rings.setdefault(model_name, _ModelRing())
            if nbytes > self.max_bytes:
                ring.oversized_total += 1
                return False
            ring.entries.append((record, nbytes))
            ring.bytes += nbytes
            ring.kept_total += 1
            self._evict_over_budget(ring)
        return True

    # -- incident stamping -------------------------------------------------

    def mark_incident(self, model_name: str, label: str) -> int:
        """Stamps ``label`` onto every trace currently resident in the
        model's ring — called by the SLO engine when a burn crosses
        its threshold and by the replica layer on a breaker
        trip/watchdog ejection, so the ring entries name the incident
        they contributed to. Returns how many records were stamped.
        Stamps are capped per record (MAX_INCIDENT_STAMPS, oldest
        rolls off) and accounted against the ring's byte budget so a
        flapping replica cannot grow resident records unboundedly."""
        stamp = {"label": label, "ts": time.time()}
        stamp_bytes = len(json.dumps(stamp, separators=(",", ":"),
                                     default=str)) + 8
        stamped = 0
        with self._lock:
            ring = self._rings.get(model_name)
            if ring is None:
                return 0
            # Entries are rebuilt with their per-entry nbytes grown by
            # the stamp, so a later eviction subtracts exactly what
            # the record accounts for — no phantom residue after a
            # stamped record churns out of the ring.
            updated: deque = deque()
            for record, nbytes in ring.entries:
                incidents = record["incidents"]
                if len(incidents) >= MAX_INCIDENT_STAMPS:
                    # Capped: the oldest stamp rolls off — account the
                    # exact size delta (labels differ in length, so
                    # "same size" would drift from resident memory).
                    popped = incidents.pop(0)
                    delta = stamp_bytes - (
                        len(json.dumps(popped, separators=(",", ":"),
                                       default=str)) + 8)
                else:
                    delta = stamp_bytes
                nbytes += delta
                ring.bytes += delta
                incidents.append(stamp)
                stamped += 1
                updated.append((record, nbytes))
            ring.entries = updated
            self._evict_over_budget(ring)
        return stamped

    # -- reading -----------------------------------------------------------

    def snapshot(self, model_name: Optional[str] = None) -> List[dict]:
        """Kept records (oldest first), one model's or all. Records are
        deep-ish copies at the top level so a concurrent
        mark_incident never mutates what a caller is serializing."""
        with self._lock:
            if model_name is not None:
                rings = {model_name: self._rings.get(model_name)}
            else:
                rings = dict(self._rings)
            out = []
            for name in sorted(rings):
                ring = rings[name]
                if ring is None:
                    continue
                for record, _nbytes in ring.entries:
                    copy = dict(record)
                    copy["incidents"] = list(record["incidents"])
                    out.append(copy)
        return out

    def stats(self) -> Dict[str, dict]:
        """Per-model ring occupancy + lifetime counters (the /v2/debug
        "flight" section)."""
        with self._lock:
            return OrderedDict(
                (name, {
                    "entries": len(ring.entries),
                    "bytes": ring.bytes,
                    "kept_total": ring.kept_total,
                    "overwritten_total": ring.overwritten_total,
                    "oversized_total": ring.oversized_total,
                })
                for name, ring in sorted(self._rings.items()))

    # -- export ------------------------------------------------------------

    def flush_chrome(self, path: str,
                     model_name: Optional[str] = None) -> int:
        """Appends the ring's records to ``path`` as chrome-trace
        complete events (the PR-6 ``trace_mode=chrome`` format, built
        by the same shared event builder — tracing.chrome_span_events
        — so the two exports can never drift; loadable in
        ui.perfetto.dev). Returns the record count written; the ring
        is NOT cleared — flight traces are evidence, and an export
        must not race an investigation."""
        from client_tpu.server.tracing import chrome_span_events

        records = self.snapshot(model_name)
        if not records:
            return 0
        try:
            import os as _os

            fresh = (not _os.path.exists(path)
                     or _os.path.getsize(path) == 0)
            with open(path, "a") as f:
                if fresh:
                    f.write("[\n")
                for index, record in enumerate(records):
                    events = chrome_span_events(
                        record["spans"], record["model"], index,
                        "flight %s %s (%s)"
                        % (record["request_id"],
                           record["trace_id"][:8], record["reason"]),
                        {"trace_id": record["trace_id"],
                         "request_id": record["request_id"],
                         "keep_reason": record["reason"]})
                    for event in events:
                        f.write(json.dumps(event, default=str) + ",\n")
        except OSError:
            return 0  # export must never fail the caller
        return len(records)
