"""HBM as a managed resource: one per-device allocator over the
ledger's (model, component) rows.

PR 15 made device memory *observable* (the DeviceLedger attributes
every byte); this module makes it *schedulable*. Every component the
ledger describes — model weights, the paged-KV slab, arena regions,
ensemble-interior hand-offs — now acquires its bytes as an
:class:`HbmLease` from the process-wide :class:`HbmAllocator`, and
three global behaviors fall out of having one owner:

* **Ledger-driven eviction.** Admission that does not fit the device
  budget pages out the *coldest* pageable leases (idle age from the
  admission-path ``touch_model`` timestamps) until it does. A request
  that loses even after eviction gets an honest retryable deferral
  (503 + Retry-After from measured restore bandwidth), never an OOM.
* **Weight paging.** Pageable models' weights move to host through
  the PR-12 overlapped-copy machinery (``fetch.offload_tree``) and
  come back chunked-parallel in reverse (``fetch.upload_tree``). The
  ledger row does not vanish at page-out — it moves to the
  ``paged_out`` side table, so ``/v2/debug`` keeps naming it.
* **Arbitration.** Each device has one admission mutex (``arb``):
  concurrent scale-ups serialize against one budget instead of racing
  each other into fragmentation; the waiter count is the arbitration
  queue depth in ``/v2/debug``.

Budget discovery: ``CLIENT_TPU_HBM_BUDGET`` (bytes, ``k``/``m``/``g``
suffixes — the simulated budget for CPU-sim CI), else the device's
``memory_stats()['bytes_limit']``, else None — accounting-only mode
where every lease is granted and nothing evicts, which is exactly the
pre-subsystem behavior. See docs/hbm.md.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from client_tpu import status_map
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import fetch
from client_tpu.utils import InferenceServerException

LOG = logging.getLogger("client_tpu.hbm")

BUDGET_ENV = "CLIENT_TPU_HBM_BUDGET"

# Restore-bandwidth prior before the first measured restore (1 GiB/s:
# conservative for PCIe hosts, pessimistic for TPU hosts). One real
# restore replaces it through the EWMA.
DEFAULT_RESTORE_BANDWIDTH = float(1 << 30)
_BANDWIDTH_EWMA_ALPHA = 0.3
MIN_RESTORE_ESTIMATE_S = 0.05
MAX_RESTORE_ESTIMATE_S = 30.0

# Bounded wait for an eviction victim's in-flight requests before its
# weights move. The policy targets the *coldest* lease — idle in any
# non-adversarial schedule — so this is a safety bound, not a budget;
# page-out proceeds at the deadline because the host copies keep a
# racing request correct (just slow), never wrong.
EVICT_DRAIN_TIMEOUT_S = 5.0

RESIDENT = "resident"
PAGED_OUT = "paged_out"
RELEASED = "released"

# Eviction heat model. Pure last-used LRU has a microsecond-
# granularity failure mode: a cold model that just served its one
# request looks "hotter" than a model serving thousands of requests
# per second whose latest touch is a hair older, so a churning cold
# tail evicts the hot set. Victims are therefore ordered by
# (recency bucket, touch-rate): leases idle in different
# LRU_BUCKET_S-sized buckets compare by idle age alone
# (coldest-first), and within the same bucket the lease with the
# lower exponentially-decayed touch rate (time constant HEAT_TAU_S)
# is the colder one.
LRU_BUCKET_S = 1.0
HEAT_TAU_S = 10.0


def _parse_budget(text: Optional[str]) -> Optional[int]:
    """``CLIENT_TPU_HBM_BUDGET`` value -> bytes (k/m/g suffixes), None
    when unset or unparseable (unparseable also warns: a typo'd budget
    silently meaning "unlimited" would be a nasty prod surprise)."""
    if not text:
        return None
    cleaned = text.strip().lower()
    multiplier = 1
    if cleaned and cleaned[-1] in ("k", "m", "g"):
        multiplier = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[cleaned[-1]]
        cleaned = cleaned[:-1]
    try:
        value = float(cleaned)
    except ValueError:
        LOG.warning("hbm: unparseable %s=%r ignored (accounting-only "
                    "mode)", BUDGET_ENV, text)
        return None
    nbytes = int(value * multiplier)
    return nbytes if nbytes > 0 else None


class WeightPager:
    """Moves one model's weights device<->host through the fetch
    machinery. ``page_out`` leaves the model holding the *host*
    copies (numpy — the CPU-sim stand-in for pinned buffers), so a
    request that races past the quiesce is slow (jit re-uploads per
    call), never wrong. ``restore`` uploads chunked-parallel and
    hands the device tree back to the model."""

    __slots__ = ("_model",)

    def __init__(self, model):
        self._model = model

    def page_out(self):
        state = self._model.weight_state()
        host_state = fetch.offload_tree(state)
        self._model.set_weight_state(host_state)
        return host_state

    def restore(self, host_state) -> None:
        device_state = fetch.upload_tree(host_state)
        self._model.set_weight_state(device_state)


class HbmLease:
    """One component's claim on one device's budget. States:
    ``resident`` (bytes count against the device), ``paged_out``
    (bytes live in ``host_state``; ledger row parked in the paged
    side table), ``released`` (terminal, idempotent)."""

    __slots__ = ("model", "component", "nbytes", "device_key",
                 "pageable", "pager", "best_effort", "state",
                 "last_used", "heat", "ledger_row", "host_state",
                 "on_page_out", "on_restore", "restoring")

    def __init__(self, model: str, component: str, nbytes: int,
                 device_key: str, pageable: bool = False,
                 pager: Optional[WeightPager] = None,
                 best_effort: bool = False):
        self.model = str(model)
        self.component = str(component)
        self.nbytes = int(nbytes)
        self.device_key = device_key
        self.pageable = bool(pageable)
        self.pager = pager
        self.best_effort = bool(best_effort)
        self.state = RESIDENT
        self.last_used = time.monotonic()
        self.heat = 0.0  # decayed touch rate (see LRU_BUCKET_S)
        self.ledger_row = None
        self.host_state = None
        # Quiesce/ready callbacks wired by the owning core: eviction
        # must stop admission + drain in-flight before weights move,
        # and flip the model READY again after restore.
        self.on_page_out: Optional[Callable[[], None]] = None
        self.on_restore: Optional[Callable[[], None]] = None
        self.restoring = False  # single-flight background restore


class _DeviceState:
    __slots__ = ("key", "capacity", "leased", "arb", "waiters")

    def __init__(self, key: str, capacity: Optional[int]):
        self.key = key
        self.capacity = capacity
        self.leased = 0
        # The per-device arbitration queue. Deliberately NOT a
        # lockish-named attribute: admission legitimately runs device
        # transfers (eviction page-outs) while serialized on it, and
        # holds the allocator's data lock only in between.
        self.arb = threading.Lock()
        self.waiters = 0


class HbmAllocator:
    """Process-wide arena-style owner of device memory (one instance
    via :func:`get`, like ``devstats.get()`` — devices are
    process-global, so all in-process cores share one budget).

    Locking: ``self._lock`` guards pure bookkeeping and is never held
    across a device transfer; ``dev.arb`` serializes admission and IS
    held across eviction/restore transfers — that serialization is
    the arbitration queue the subsystem exists to provide."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 stats: Optional["devstats_mod.DeviceStats"] = None):
        self._stats = stats or devstats_mod.get()
        self._budget_override = budget_bytes
        self._lock = threading.Lock()
        self._devices: Dict[str, _DeviceState] = {}
        self._by_model: Dict[str, List[HbmLease]] = {}
        # (model, component, reason) -> count
        self._evictions: Dict[Tuple[str, str, str], int] = {}
        self._pageouts: Dict[str, int] = {}
        self._restore_hists: Dict[str, object] = {}
        self._restore_bw: Optional[float] = None
        self._deferrals = 0

    # -- devices -----------------------------------------------------------

    def _discover_capacity(self, device_key: str) -> Optional[int]:
        if self._budget_override is not None:
            return int(self._budget_override)
        budget = _parse_budget(os.environ.get(BUDGET_ENV))
        if budget is not None:
            return budget
        try:
            import jax

            for device in jax.local_devices():
                key = "%s-%d" % (device.platform.upper(), device.id)
                if key == device_key:
                    limit = (device.memory_stats() or {}).get(
                        "bytes_limit")
                    return int(limit) if limit else None
        except Exception:  # noqa: BLE001 — no runtime: unlimited
            pass
        return None

    def _device(self, device_key: Optional[str] = None) -> _DeviceState:
        if device_key is None:
            device_key = self._stats.device_keys()[0]
        with self._lock:
            dev = self._devices.get(device_key)
        if dev is not None:
            return dev
        capacity = self._discover_capacity(device_key)
        with self._lock:
            dev = self._devices.get(device_key)
            if dev is None:
                dev = _DeviceState(device_key, capacity)
                self._devices[device_key] = dev
            return dev

    # -- lease lifecycle ---------------------------------------------------

    def lease(self, model: str, component: str, nbytes: int,
              device_key: Optional[str] = None, pageable: bool = False,
              pager: Optional[WeightPager] = None,
              best_effort: bool = False,
              reason: str = "admission") -> Optional[HbmLease]:
        """Claims ``nbytes`` on a device, evicting coldest pageable
        leases if the budget demands it; raises an honest retryable
        deferral when even eviction cannot fit it. ``best_effort``
        leases (ensemble-interior regions, adopted weights) never
        evict and never raise — they charge the budget and let
        rebalance settle accounts later. Returns None for empty
        sizes: nothing to account, nothing to leak."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return None
        dev = self._device(device_key)
        new_lease = HbmLease(model, component, nbytes, dev.key,
                             pageable=pageable, pager=pager,
                             best_effort=best_effort)
        if best_effort or dev.capacity is None:
            with self._lock:
                dev.leased += nbytes
        else:
            self._admit(dev, nbytes, exclude_model=new_lease.model,
                        reason=reason)
        try:  # accounting must never block the data plane
            new_lease.ledger_row = self._stats.ledger.register(
                new_lease.model, new_lease.component, nbytes)
        except Exception:  # noqa: BLE001
            LOG.warning("hbm: ledger register failed for %s/%s",
                        model, component, exc_info=True)
        with self._lock:
            self._by_model.setdefault(new_lease.model, []).append(
                new_lease)
        return new_lease

    def release(self, lease: Optional[HbmLease]) -> None:
        """Idempotent: frees device bytes (resident) or drops the host
        copy (paged_out); the ledger row goes with it either way."""
        if lease is None:
            return
        with self._lock:
            state, lease.state = lease.state, RELEASED
            if state == RELEASED:
                return
            lease.restoring = False
            dev = self._devices.get(lease.device_key)
            if state == RESIDENT and dev is not None:
                dev.leased = max(dev.leased - lease.nbytes, 0)
            leases = self._by_model.get(lease.model)
            if leases is not None:
                try:
                    leases.remove(lease)
                except ValueError:
                    pass
                if not leases:
                    self._by_model.pop(lease.model, None)
        row, lease.ledger_row = lease.ledger_row, None
        lease.host_state = None
        try:  # accounting must never block the data plane
            if state == RESIDENT:
                self._stats.ledger.release(row)
            elif state == PAGED_OUT:
                self._stats.ledger.unmark_paged(
                    lease.model, lease.component, lease.nbytes)
        except Exception:  # noqa: BLE001
            LOG.warning("hbm: ledger release failed for %s/%s",
                        lease.model, lease.component, exc_info=True)

    def release_model(self, model: str) -> int:
        """Unload teardown: every lease of ``model`` goes, paged-out
        host copies included. Returns the count released."""
        with self._lock:
            doomed = list(self._by_model.get(str(model), ()))
        for lease in doomed:
            self.release(lease)
        return len(doomed)

    def touch_model(self, model: str) -> None:
        """Admission hot path: stamps every lease of ``model`` so the
        eviction policy sees it as hot. Lock-only, never raises."""
        now = time.monotonic()
        with self._lock:
            for lease in self._by_model.get(str(model), ()):
                elapsed = max(now - lease.last_used, 0.0)
                lease.heat = (lease.heat
                              * math.exp(-elapsed / HEAT_TAU_S) + 1.0)
                lease.last_used = now

    def weight_lease(self, model: str) -> Optional[HbmLease]:
        with self._lock:
            for lease in self._by_model.get(str(model), ()):
                if lease.component == "weights" \
                        and lease.state != RELEASED:
                    return lease
        return None

    # -- admission + eviction ----------------------------------------------

    def _admit(self, dev: _DeviceState, nbytes: int,
               exclude_model: str, reason: str) -> None:
        with self._lock:
            dev.waiters += 1
        dev.arb.acquire()
        try:
            with self._lock:
                dev.waiters -= 1
            self._reserve(dev, nbytes, exclude_model, reason)
        finally:
            dev.arb.release()

    def _reserve(self, dev: _DeviceState, nbytes: int,
                 exclude_model: str, reason: str) -> None:
        """Caller holds ``dev.arb``. Reserves ``nbytes`` against the
        budget, paging out coldest pageable leases until it fits, or
        raises the honest deferral."""
        if dev.capacity is None:
            with self._lock:
                dev.leased += nbytes
            return
        if nbytes > dev.capacity:
            # Permanent, not a pressure condition: no amount of
            # eviction or waiting makes the component fit, so the
            # error is non-retryable (a Retry-After here would have
            # well-behaved clients retrying forever).
            raise InferenceServerException(
                "component needs %d bytes but device %s has %d total: "
                "it can never fit this budget"
                % (nbytes, dev.key, dev.capacity),
                status="INVALID_ARGUMENT")
        skip: set = set()
        while True:
            with self._lock:
                if dev.capacity - dev.leased >= nbytes:
                    dev.leased += nbytes
                    return
                victim = self._coldest_locked(dev, exclude_model, skip)
                if victim is None:
                    self._deferrals += 1
                    free = max(dev.capacity - dev.leased, 0)
            if victim is None:
                raise status_map.retryable_error(
                    "HBM budget exhausted on %s: need %d bytes, %d "
                    "free, nothing evictable (every resident lease is "
                    "hot or non-pageable)" % (dev.key, nbytes, free),
                    status="RESOURCE_EXHAUSTED",
                    retry_after_s=self.restore_estimate_s(nbytes))
            try:
                if self._do_page_out(victim):
                    self._count_eviction(victim, reason)
                else:  # concurrently released/paged: pick another
                    skip.add(id(victim))
            except Exception:  # noqa: BLE001 — a victim whose page-
                # out fails stays resident; skip it or the loop spins.
                LOG.warning("hbm: eviction page-out of %s/%s failed",
                            victim.model, victim.component,
                            exc_info=True)
                skip.add(id(victim))

    @staticmethod
    def _cold_key(lease: HbmLease) -> Tuple[int, float]:
        """Victim ordering: recency bucket first (coldest-first by
        idle age), decayed touch rate within a bucket — so a cold
        model's single just-served request cannot outrank a model
        serving thousands per second whose latest touch is a
        microsecond older."""
        return (int(lease.last_used / LRU_BUCKET_S), lease.heat)

    def _coldest_locked(self, dev: _DeviceState, exclude_model: str,
                        skip: set) -> Optional[HbmLease]:
        coldest = None
        for leases in self._by_model.values():
            for candidate in leases:
                if (candidate.device_key != dev.key
                        or candidate.state != RESIDENT
                        or not candidate.pageable
                        or candidate.pager is None
                        or candidate.model == exclude_model
                        or id(candidate) in skip):
                    continue
                if coldest is None \
                        or self._cold_key(candidate) \
                        < self._cold_key(coldest):
                    coldest = candidate
        return coldest

    def _count_eviction(self, victim: HbmLease, reason: str) -> None:
        with self._lock:
            key = (victim.model, victim.component, str(reason))
            self._evictions[key] = self._evictions.get(key, 0) + 1

    # -- paging ------------------------------------------------------------

    def _do_page_out(self, lease: HbmLease) -> bool:
        """Device->host for one lease. Caller holds ``dev.arb`` (all
        page-outs serialize with admission); never holds
        ``self._lock`` — the quiesce waits on in-flight requests and
        the copy is a device transfer. Returns True when the lease
        committed to ``paged_out``, False when a concurrent
        release/page-out made it a no-op. The RELEASED re-checks are
        load-bearing: release()/release_model() take only
        ``self._lock``, so an unload can land at any point during the
        copy — a RELEASED lease is terminal and must never be
        resurrected or have its bytes settled twice."""
        with self._lock:
            if lease.state != RESIDENT:
                return False
        quiesce = lease.on_page_out
        if quiesce is not None:
            quiesce()
        try:
            lease.host_state = lease.pager.page_out()
        except Exception:
            # Weights are still resident: undo the quiesce so the
            # model does not strand UNAVAILABLE behind a failed copy
            # (unless a racing release already tore the model down —
            # then there is nothing left to mark ready).
            ready = lease.on_restore
            with self._lock:
                released = lease.state == RELEASED
            if ready is not None and not released:
                ready()
            raise
        with self._lock:
            if lease.state != RESIDENT:
                # Released mid-copy: the teardown already settled the
                # device bytes and the ledger; the host copy just
                # dies here.
                lease.host_state = None
                return False
            lease.state = PAGED_OUT
            row, lease.ledger_row = lease.ledger_row, None
            dev = self._devices.get(lease.device_key)
            if dev is not None:
                dev.leased = max(dev.leased - lease.nbytes, 0)
            self._pageouts[lease.model] = \
                self._pageouts.get(lease.model, 0) + 1
        try:  # accounting must never block the data plane
            moved = self._stats.ledger.mark_paged(row)
            if not moved:
                # Row was never registered (load-measure failure):
                # park the bytes directly so the paged set still
                # names this component.
                self._stats.ledger.mark_paged_bytes(
                    lease.model, lease.component, lease.nbytes)
            with self._lock:
                released = lease.state == RELEASED
            if released:
                # release() raced the ledger move: its unmark ran
                # before the bytes were parked, so undo the parking
                # (idempotent — unmark clamps at what is held).
                self._stats.ledger.unmark_paged(
                    lease.model, lease.component, lease.nbytes)
        except Exception:  # noqa: BLE001
            LOG.warning("hbm: ledger page-out failed for %s/%s",
                        lease.model, lease.component, exc_info=True)
        return True

    def page_out(self, lease: Optional[HbmLease],
                 reason: str = "scale_to_zero") -> int:
        """Voluntary page-out (the autoscaler's scale-to-zero): moves
        one resident pageable lease to host and returns the device
        bytes freed (0 when there was nothing to do)."""
        if lease is None or lease.pager is None:
            return 0
        dev = self._device(lease.device_key)
        dev.arb.acquire()
        try:
            if not self._do_page_out(lease):
                return 0
        finally:
            dev.arb.release()
        return lease.nbytes

    def claim_restore(self, lease: HbmLease) -> bool:
        """Single-flight guard for background restore kicks: True for
        exactly one caller until the restore settles."""
        with self._lock:
            if lease.state != PAGED_OUT or lease.restoring:
                return False
            lease.restoring = True
            return True

    def restore(self, lease: Optional[HbmLease],
                reason: str = "restore") -> bool:
        """Host->device: re-admits the lease against the budget (may
        evict colder leases; may raise the honest deferral — the
        "losing scale-up" of the arbitration design), uploads through
        ``fetch.upload_tree``, updates the measured restore-bandwidth
        EWMA, and flips the model READY via ``on_restore``. True when
        the lease is resident on return."""
        if lease is None:
            return False
        dev = self._device(lease.device_key)
        with self._lock:
            dev.waiters += 1
        dev.arb.acquire()
        try:
            with self._lock:
                dev.waiters -= 1
                if lease.state != PAGED_OUT:
                    lease.restoring = False
                    return lease.state == RESIDENT
                # Pin the host copy now: a release() racing this
                # restore nulls lease.host_state without holding
                # dev.arb, and the upload must not read a torn-down
                # None (the local reference keeps the tree alive).
                host_state = lease.host_state
            try:
                self._reserve(dev, lease.nbytes, lease.model, reason)
            except Exception:
                with self._lock:
                    lease.restoring = False
                raise
            started_ns = time.monotonic_ns()
            try:
                lease.pager.restore(host_state)
            except Exception:
                with self._lock:
                    dev.leased = max(dev.leased - lease.nbytes, 0)
                    lease.restoring = False
                raise
            elapsed_s = max((time.monotonic_ns() - started_ns) / 1e9,
                            1e-9)
            with self._lock:
                # The transfer was real either way: let it price
                # future Retry-After estimates.
                bandwidth = lease.nbytes / elapsed_s
                if self._restore_bw is None:
                    self._restore_bw = bandwidth
                else:
                    self._restore_bw = (
                        _BANDWIDTH_EWMA_ALPHA * bandwidth
                        + (1.0 - _BANDWIDTH_EWMA_ALPHA)
                        * self._restore_bw)
                if lease.state == RELEASED:
                    # unload_model raced the upload: release() saw
                    # PAGED_OUT and settled the ledger but left the
                    # device bytes alone, so the admission reserve is
                    # ours to give back; the fresh device tree dies
                    # with the lease. RELEASED is terminal — do not
                    # resurrect it.
                    dev_state = self._devices.get(lease.device_key)
                    if dev_state is not None:
                        dev_state.leased = max(
                            dev_state.leased - lease.nbytes, 0)
                    lease.restoring = False
                    lease.host_state = None
                    return False
                lease.state = RESIDENT
                lease.host_state = None
                lease.restoring = False
                lease.last_used = time.monotonic()
            self._observe_restore(lease.model, elapsed_s * 1e6)
            try:  # accounting must never block the data plane
                self._stats.ledger.unmark_paged(
                    lease.model, lease.component, lease.nbytes)
                row = self._stats.ledger.register(
                    lease.model, lease.component, lease.nbytes)
                try:
                    with self._lock:
                        if lease.state != RELEASED:
                            lease.ledger_row, row = row, None
                finally:
                    if row is not None:
                        # Released between the RESIDENT commit and
                        # the re-register (release saw no row to
                        # drop): the fresh row must not outlive the
                        # lease.
                        self._stats.ledger.release(row)
            except Exception:  # noqa: BLE001
                LOG.warning("hbm: ledger restore failed for %s/%s",
                            lease.model, lease.component,
                            exc_info=True)
            ready = lease.on_restore
            with self._lock:
                still_resident = lease.state == RESIDENT
            if ready is not None and still_resident:
                ready()
            return still_resident
        finally:
            dev.arb.release()

    # -- weights adoption --------------------------------------------------

    def adopt_weights(self, model_obj, row=None,
                      on_page_out: Optional[Callable[[], None]] = None,
                      on_restore: Optional[Callable[[], None]] = None
                      ) -> Optional[HbmLease]:
        """Post-load adoption of a model's weights: the load
        measurement already registered the ``weights`` ledger row, so
        the lease adopts it (no double accounting), charges the
        budget post-hoc, and rebalances — paging out *other* models'
        coldest leases if this adoption overflowed the device. Never
        raises: the load already happened; the honest pre-admission
        path is :meth:`restore`."""
        name = str(getattr(model_obj, "name", model_obj))
        nbytes = int(getattr(row, "nbytes", 0) or 0)
        if nbytes <= 0:
            try:
                nbytes = devstats_mod.model_array_bytes(model_obj)
            except Exception:  # noqa: BLE001
                nbytes = 0
        if nbytes <= 0:
            return None
        previous = self.weight_lease(name)
        if previous is not None:
            if row is not None:
                # The re-load measurement already replaced the
                # ledger's weights component wholesale
                # (release_component), so the old lease's row handle
                # is stale — releasing it would subtract from the
                # fresh row.
                previous.ledger_row = None
            self.release(previous)  # re-load replaces, never doubles
        pageable = bool(getattr(model_obj, "pageable_weights", False))
        pager = None
        if pageable:
            try:
                pager = WeightPager(model_obj) \
                    if model_obj.weight_state() is not None else None
            except Exception:  # noqa: BLE001
                pager = None
            pageable = pager is not None
        dev = self._device(None)
        new_lease = HbmLease(name, "weights", nbytes, dev.key,
                             pageable=pageable, pager=pager,
                             best_effort=True)
        new_lease.on_page_out = on_page_out
        new_lease.on_restore = on_restore
        new_lease.ledger_row = row
        if row is None:
            try:  # accounting must never block the data plane
                new_lease.ledger_row = self._stats.ledger.register(
                    name, "weights", nbytes)
            except Exception:  # noqa: BLE001
                LOG.warning("hbm: weights ledger register failed for "
                            "%s", name, exc_info=True)
        with self._lock:
            dev.leased += nbytes
            self._by_model.setdefault(name, []).append(new_lease)
        self._rebalance(dev, protect=name, reason="admission")
        return new_lease

    def _rebalance(self, dev: _DeviceState, protect: str,
                   reason: str) -> None:
        """Post-hoc pressure relief after an adoption: pages out
        coldest pageable leases until the device fits its budget.
        Never raises — when nothing is evictable the device runs
        honestly overcommitted (the pre-subsystem behavior)."""
        if dev.capacity is None:
            return
        dev.arb.acquire()
        try:
            skip: set = set()
            while True:
                with self._lock:
                    if dev.leased <= dev.capacity:
                        return
                    victim = self._coldest_locked(dev, protect, skip)
                if victim is None:
                    return
                try:
                    if self._do_page_out(victim):
                        self._count_eviction(victim, reason)
                    else:  # concurrently released/paged
                        skip.add(id(victim))
                except Exception:  # noqa: BLE001
                    LOG.warning("hbm: rebalance page-out of %s/%s "
                                "failed", victim.model,
                                victim.component, exc_info=True)
                    skip.add(id(victim))
        finally:
            dev.arb.release()

    # -- estimates + introspection -----------------------------------------

    def restore_bandwidth(self) -> float:
        with self._lock:
            return self._restore_bw or DEFAULT_RESTORE_BANDWIDTH

    def restore_estimate_s(self, nbytes: int) -> float:
        """Honest Retry-After for a cold start: bytes over the
        measured restore-bandwidth EWMA, clamped to sane bounds."""
        bandwidth = max(self.restore_bandwidth(), 1.0)
        estimate = float(max(int(nbytes), 0)) / bandwidth
        return min(max(estimate, MIN_RESTORE_ESTIMATE_S),
                   MAX_RESTORE_ESTIMATE_S)

    def _observe_restore(self, model: str, micros: float) -> None:
        try:  # accounting must never block the data plane
            from client_tpu.server.telemetry import LatencyHistogram

            with self._lock:
                hist = self._restore_hists.get(model)
                if hist is None:
                    hist = self._restore_hists.setdefault(
                        model, LatencyHistogram())
            hist.observe(micros)
        except Exception:  # noqa: BLE001
            LOG.warning("hbm: restore histogram failed", exc_info=True)

    def paged_out_models(self) -> List[str]:
        with self._lock:
            return sorted({
                lease.model
                for leases in self._by_model.values()
                for lease in leases if lease.state == PAGED_OUT})

    def debug_snapshot(self) -> dict:
        """The ``hbm`` section of GET /v2/debug (cardinality-bounded
        by the ledger's own model/component caps)."""
        now = time.monotonic()
        with self._lock:
            devices = {}
            for key in sorted(self._devices):
                dev = self._devices[key]
                free = None
                if dev.capacity is not None:
                    free = max(dev.capacity - dev.leased, 0)
                devices[key] = {
                    "capacity_bytes": dev.capacity,
                    "leased_bytes": dev.leased,
                    "free_bytes": free,
                    "arbitration_queue_depth": dev.waiters,
                }
            leases = []
            paged_out = set()
            for model in sorted(self._by_model):
                for lease in self._by_model[model]:
                    leases.append({
                        "model": lease.model,
                        "component": lease.component,
                        "nbytes": lease.nbytes,
                        "device": lease.device_key,
                        "state": lease.state,
                        "pageable": lease.pageable,
                        "idle_s": round(now - lease.last_used, 3),
                    })
                    if lease.state == PAGED_OUT:
                        paged_out.add(lease.model)
            evictions = [
                {"model": model, "component": component,
                 "reason": reason, "count": count}
                for (model, component, reason), count
                in sorted(self._evictions.items())]
            deferrals = self._deferrals
        return {
            "devices": devices,
            "leases": leases,
            "paged_out": sorted(paged_out),
            "evictions": evictions,
            "deferrals": deferrals,
            "restore_bandwidth_bytes_per_s":
                int(self.restore_bandwidth()),
        }

    # -- exposition --------------------------------------------------------

    def render_metrics(self) -> List[str]:
        """Prometheus exposition for the allocator families (joins
        the devstats block in ``core.metrics_text``)."""
        lines: List[str] = []

        def family(name, kind, help_text, rows):
            if not rows:
                return
            lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)

        free_rows = []
        with self._lock:
            for key in sorted(self._devices):
                dev = self._devices[key]
                if dev.capacity is None:
                    continue
                free_rows.append(
                    'tpu_hbm_free_bytes{tpu_uuid="%s"} %d'
                    % (key, max(dev.capacity - dev.leased, 0)))
            eviction_items = sorted(self._evictions.items())
            pageout_items = sorted(self._pageouts.items())
            hist_items = sorted(self._restore_hists.items())
        family("tpu_hbm_free_bytes", "gauge",
               "Allocator-visible free HBM per device (budget minus "
               "resident leases)", free_rows)
        family("tpu_hbm_evictions_total", "counter",
               "Ledger-driven evictions of pageable components, by "
               "victim and trigger",
               ['tpu_hbm_evictions_total{model="%s",component="%s",'
                'reason="%s"} %d' % (model, component, reason, count)
                for (model, component, reason), count
                in eviction_items])
        family("tpu_weight_pageout_total", "counter",
               "Weight page-outs to host (evictions plus "
               "scale-to-zero)",
               ['tpu_weight_pageout_total{model="%s"} %d'
                % (model, count) for model, count in pageout_items])
        hist_rows: List[str] = []
        try:
            from client_tpu.server.telemetry import ServerTelemetry

            for model, hist in hist_items:
                snap = hist.snapshot()
                if snap["count"]:
                    hist_rows.extend(ServerTelemetry._histogram_rows(
                        "tpu_weight_restore_us", 'model="%s"' % model,
                        snap, with_exemplars=False))
        except Exception:  # noqa: BLE001
            LOG.warning("hbm: restore histogram render failed",
                        exc_info=True)
        family("tpu_weight_restore_us", "histogram",
               "Host->device weight restore wall time (histogram)",
               hist_rows)
        return lines


# -- process-wide singleton -------------------------------------------------

_SINGLETON: Optional[HbmAllocator] = None
_SINGLETON_LOCK = threading.Lock()


def get() -> HbmAllocator:
    """The process-wide allocator (devices are process-global; all
    in-process cores share one budget, exactly like devstats.get())."""
    global _SINGLETON
    if _SINGLETON is None:
        with _SINGLETON_LOCK:
            if _SINGLETON is None:
                _SINGLETON = HbmAllocator()
    return _SINGLETON
