"""In-process replica serving: per-device fault domains with
health-routed dispatch and automatic recovery.

A model that declares an ``instance_group`` (count N) is served by N
:class:`_Replica` instances — each one its own model executable on its
own single-threaded device queue — behind a :class:`ReplicaSet` router
that sits between the PR-1 dynamic batcher and execution. The router
is the in-process twin of the PR-4 :class:`~client_tpu.robust.
EndpointPool`: the same least-expected-completion-time score
(``(outstanding + 1) * EWMA latency``), the same per-target
:class:`~client_tpu.robust.CircuitBreaker`, the same sticky sequence
routing — applied to devices inside one server instead of endpoints
across servers.

Each replica is a **fault domain**:

* **Watchdog.** Every execution is bounded by the model's
  ``replica_watchdog_us`` deadline. A replica that blows it is marked
  UNHEALTHY immediately (a hung device queue would otherwise wedge
  every batch routed to it) and the waiting batch is re-dispatched to
  a healthy sibling. The stuck worker thread is abandoned — its
  executor is replaced wholesale at recovery, never joined.
* **Circuit breaker.** Execution failures settle the replica's
  breaker exactly like endpoint failures settle the pool's (definitive
  client errors count as health, see :func:`~client_tpu.robust.
  _breaker_resolve`); repeated failures open it and eject the replica
  from routing.
* **Bounded re-dispatch.** A batch that fails on one replica is
  re-dispatched to a healthy sibling exactly ONCE — masking a
  single-replica fault costs one extra execution, never a retry storm.
  Deterministic client errors (bad shapes and friends) are never
  re-dispatched: the sibling would fail them identically.
* **Supervisor self-healing.** A background thread watches unhealthy
  replicas, re-initializes an ejected replica's executable and weights
  (a fresh instance from the model factory, on a fresh device-queue
  thread), half-open-probes it with a canary execution through the
  full fault-injection path, and readmits it on success — so a
  recovered replica is found by the supervisor, not by sacrificial
  traffic.

Sequence slots pin sticky to a replica until that replica is ejected
(implicit per-sequence state is replica-local), mirroring EndpointPool
sequence stickiness.

Replica-targeted chaos (``replica=model:index`` + the ``hang_ms``
fault kind in :mod:`client_tpu.server.chaos`) injects faults into
exactly one replica's execution path — the blast-radius scenario the
CI replica smoke gates on.

**Mesh slices** (PR 20, :mod:`client_tpu.server.mesh`): a model that
declares a ``shard_mesh`` (e.g. tp=4) is served by replicas that are
*slices* — each one a disjoint ``slice_width``-device block carrying a
sharded executable built by the factory's ``mesh=`` contract, with
per-device HBM leases/ledger rows booked at admission. Everything
above stays word-for-word true with "device" read as "device set": the
watchdog bounds the slice's fused sharded call, one sick chip (chaos
``device=<id>``) fails executions that touch it and so ejects the
whole slice, busy time and watchdog/breaker evidence are attributed to
every member device, and scale_up/scale_down admit/drain whole slices
against the HBM arbitration mutex on every member.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Callable, Dict, List, Optional

import numpy as np

from client_tpu import status_map
from client_tpu.robust import CLIENT_ERROR_STATUSES, CircuitBreaker
from client_tpu.server import chaos
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import mesh as mesh_mod
from client_tpu.utils import InferenceServerException, triton_to_np_dtype

_LOG = logging.getLogger("client_tpu.server.replicas")

# Per-execution watchdog when the model doesn't set
# replica_watchdog_us: generous enough for any sane CPU-sim execution,
# tight enough that a hung replica costs seconds, not a drain timeout.
DEFAULT_WATCHDOG_US = 5_000_000
# Consecutive execution failures before the breaker ejects a replica.
DEFAULT_FAILURE_THRESHOLD = 3
# Breaker reset timeout AND the supervisor's probe pace: how long an
# ejected replica rests before the supervisor re-initializes and
# canary-probes it.
DEFAULT_RECOVERY_S = 1.0
# Every Nth routed execution round-robins the healthy candidates
# instead of taking the least-expected-completion-time minimum (the
# in-process, deterministic form of EndpointPool's 2% exploration):
# keeps every replica's EWMA fresh so one slow cold execution cannot
# starve a fault domain out of the rotation.
EXPLORE_EVERY = 16


def wants_replicas(model) -> bool:
    """A model opts into replica serving by declaring an instance
    group (``instance_group_count >= 1``). Count 1 still engages the
    layer — one fault domain with a watchdog and self-healing — while
    0 (the default) keeps the legacy direct path."""
    return int(getattr(model, "instance_group_count", 0) or 0) >= 1


class _Replica:
    """One fault domain: its own model executable on its own
    single-threaded device queue (executions on one replica are
    serialized, mirroring a device that runs one program at a time;
    executions on distinct replicas are concurrent).

    Mutable routing fields (outstanding / EWMA / counters) are guarded
    by the SET's lock — routing reads the whole fleet atomically, like
    EndpointPool. The breaker has its own lock."""

    __slots__ = ("index", "model", "executor", "breaker", "hung",
                 "outstanding", "ewma_latency_s", "requests", "failures",
                 "execution_count", "exec_ns", "ejected_count",
                 "readmitted_count", "generation", "ledger_row",
                 "mesh_slice", "device_ids", "device_keys", "slice_res")

    def __init__(self, index: int, model, breaker: CircuitBreaker,
                 mesh_slice=None):
        self.index = index
        self.model = model
        self.breaker = breaker
        # Device-ledger row for this replica's own executable (None
        # when the replica shares the base instance — the load-time
        # weights row already covers that memory).
        self.ledger_row = None
        # Mesh-slice serving (PR 20): the device block this replica IS
        # (None = classic per-device replica). device_ids feed chaos
        # device targeting; device_keys feed per-member busy/evidence
        # attribution; slice_res holds the per-device HBM leases.
        self.mesh_slice = mesh_slice
        self.device_ids = tuple(mesh_slice.device_ids) \
            if mesh_slice is not None else ()
        self.device_keys = tuple(mesh_slice.device_keys) \
            if mesh_slice is not None else ()
        self.slice_res = None
        self.executor: Optional[ThreadPoolExecutor] = None
        # Watchdog verdict: the replica's device queue stopped
        # answering. Distinct from the breaker (which needs repeated
        # failures) because a hang gives no per-request failure signal
        # to accumulate — one blown deadline is the whole story.
        self.hung = False
        self.outstanding = 0
        self.ewma_latency_s = 0.0
        self.requests = 0
        self.failures = 0
        self.execution_count = 0
        self.exec_ns = 0
        self.ejected_count = 0
        self.readmitted_count = 0
        # Bumped at every re-initialization so thread names identify
        # the CURRENT device queue in a stack dump (abandoned hung
        # threads keep their old generation's name).
        self.generation = 0

    def healthy(self) -> bool:
        return not self.hung and self.breaker.state == CircuitBreaker.CLOSED


class ReplicatedModel:
    """Thin execution proxy handed to the schedulers in place of the
    base model: attribute reads delegate to the base model (config
    knobs, tensor specs), ``infer`` routes through the ReplicaSet.
    Only ever used as an execution target — the core keeps operating
    on the base model for metadata/config/stats."""

    def __init__(self, replica_set: "ReplicaSet"):
        self._set = replica_set
        self._base = replica_set.base

    def __getattr__(self, name):
        return getattr(self._base, name)

    def infer(self, inputs, parameters: Optional[dict] = None):
        # Sticky sequence routing rides the parameters: a sequence_id
        # pins the sequence's steps to one replica until it is ejected
        # (see ReplicaSet.infer).
        return self._set.infer(inputs, parameters)


class ReplicaSet:
    """N per-device replicas of one model plus the health-routed
    router, watchdog, and self-healing supervisor described in the
    module docstring.

    ``factory`` re-instantiates the model for replicas 1..N-1 and for
    supervisor re-initialization; when it is missing (or degenerately
    returns the same instance — a repository entry registered with
    ``add_model``'s resurrection lambda), the replicas share the base
    executable: fault isolation degrades to per-replica device queues
    and watchdogs, and re-initialization only replaces the queue
    thread, not the weights."""

    def __init__(self, model, factory: Optional[Callable] = None,
                 count: Optional[int] = None,
                 watchdog_us: Optional[int] = None,
                 failure_threshold: Optional[int] = None,
                 recovery_s: Optional[float] = None,
                 scope_fn: Optional[Callable[[], Optional[str]]] = None,
                 event_hook: Optional[Callable[[str, str], None]] = None):
        self.base = model
        # Lifecycle notification (event_hook(model_name, label)): the
        # core wires this to the flight recorder so breaker trips and
        # watchdog ejections stamp the anomaly traces that led up to
        # them. Called OUTSIDE the set's lock; failures are swallowed
        # (forensics must never affect serving).
        self._event_hook = event_hook
        self.name = str(getattr(model, "name", "model"))
        self._factory = factory
        count = int(count if count is not None
                    else getattr(model, "instance_group_count", 0) or 1)
        self.count = max(count, 1)
        watchdog_us = int(watchdog_us if watchdog_us is not None
                          else getattr(model, "replica_watchdog_us", 0) or 0)
        self._watchdog_s = (watchdog_us or DEFAULT_WATCHDOG_US) / 1e6
        self._failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else getattr(model, "replica_failure_threshold", 0)
            or DEFAULT_FAILURE_THRESHOLD)
        self._recovery_s = float(
            recovery_s if recovery_s is not None
            else getattr(model, "replica_recovery_s", 0)
            or DEFAULT_RECOVERY_S)
        # Chaos scope of the owning core, read per execution so an
        # in-process fleet's scoped faults reach replica executions.
        self._scope_fn = scope_fn
        # Mesh-slice serving (PR 20): a shard_mesh declaration turns
        # each replica into a slice_width-device slice. Slices need a
        # real factory (the mesh= contract); without one the set
        # degrades to classic shared-base replicas with a warning.
        self._shard_axes = mesh_mod.shard_axes(model)
        self.slice_width = mesh_mod.slice_width(model)
        self.sharded = bool(self._shard_axes)
        if self.sharded and factory is None:
            _LOG.warning(
                "model '%s' declares shard_mesh %s but has no factory; "
                "serving UNSHARDED shared-base replicas", self.name,
                self._shard_axes)
            self._shard_axes = []
            self.slice_width = 1
            self.sharded = False
        try:
            import jax

            self._ndev = max(len(jax.devices()), 1)
        except Exception:  # noqa: BLE001 — device-less unit tests
            self._ndev = 1
        # Per-device fault evidence (watchdog/breaker failures keyed by
        # device_key): under tp>1 one sick chip's trail must name the
        # chip, not just the slice. Guarded by the set's lock.
        self._device_evidence: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._sticky: Dict[object, int] = {}
        # Exploration counter (EndpointPool's 2% random exploration,
        # made deterministic): every EXPLORE_EVERYth routed execution
        # round-robins the healthy candidates instead of taking the
        # min score, so a replica whose EWMA was seeded by one slow
        # cold execution is periodically re-measured instead of
        # starved forever.
        self._route_count = 0
        # Set-level counters (the tpu_replica_* Prometheus families).
        self.ejections = 0
        self.readmissions = 0
        self.redispatches = 0
        self.watchdog_trips = 0
        self.probes = 0
        # Dynamic-resize lifecycle (driven by the autoscale
        # controller; see scale_up/scale_down below).
        self.scale_ups = 0
        self.scale_downs = 0
        self.canary_rejects = 0
        self.replicas: List[_Replica] = []
        for index in range(self.count):
            mesh_slice = self._plan_slice(index)
            if mesh_slice is not None:
                # Sharded: EVERY replica (index 0 included) is a fresh
                # slice-sharded executable from the factory; the base
                # model stays the metadata/config surface only.
                instance = self._new_instance(mesh_slice)
            else:
                instance = model if index == 0 else self._new_instance()
            replica = _Replica(index, instance, CircuitBreaker(
                failure_threshold=self._failure_threshold,
                reset_timeout_s=self._recovery_s),
                mesh_slice=mesh_slice)
            self._seed_devices(replica)
            self._start_queue(replica)
            self._register_ledger(replica, instance)
            self.replicas.append(replica)
        # Indexes are never reused across resizes: a drained replica's
        # index (and its metric series, sticky pins, chaos target ids)
        # dies with it, so list POSITION is not index — lookups scan.
        self._next_index = self.count
        self.proxy = ReplicatedModel(self)
        self._stopping = False
        self._stop = threading.Event()
        # Supervisor pace: a fraction of the recovery timeout so a
        # replica is probed soon after its breaker's rest expires.
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name="replica-supervisor-%s" % self.name)
        self._supervisor.start()

    # -- construction / teardown ----------------------------------------

    def _plan_slice(self, index: int):
        """The deterministic device block for replica ``index`` (None
        when the set is unsharded)."""
        if not self.sharded:
            return None
        return mesh_mod.plan_slice(self._shard_axes, index)

    def _seed_devices(self, replica: _Replica) -> None:
        """Fills the replica's device identity: slice members when
        sharded, else the single device its index maps to (the same
        index-modulo placement devstats uses for busy attribution) —
        so chaos ``device=<id>`` targeting and per-device evidence
        work uniformly across both serving shapes."""
        if replica.mesh_slice is not None:
            return  # _Replica.__init__ copied the slice's devices
        replica.device_ids = (replica.index % self._ndev,)
        replica.device_keys = (
            devstats_mod.get().device_key_for_index(replica.index),)

    def _new_instance(self, mesh_slice=None):
        """A fresh executable+weights, or the shared base when no real
        factory exists (see class docstring). With ``mesh_slice`` the
        factory is invoked through the mesh= contract so the instance
        comes up sharded over exactly that slice's devices."""
        if self._factory is None:
            return self.base
        try:
            if mesh_slice is not None:
                instance = mesh_mod.build_instance(self._factory,
                                                   mesh_slice)
            else:
                instance = self._factory()
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            _LOG.warning("replica factory for '%s' failed (%s); "
                         "sharing the base executable", self.name, e)
            return self.base
        if instance is None:
            return self.base
        if instance is not self.base:
            # Compile/warm the fresh executable BEFORE it enters
            # routing so the first routed request doesn't eat a cold
            # jit under the execution watchdog.
            try:
                warmup = getattr(instance, "warmup", None)
                if callable(warmup):
                    with devstats_mod.get().compile_scope(
                            self.name, "replica_warmup"):
                        warmup()
            except Exception:  # noqa: BLE001 — serving will judge it
                pass
        return instance

    def _start_queue(self, replica: _Replica) -> None:
        """(Re)creates the replica's single-threaded device queue."""
        replica.generation += 1
        replica.executor = ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix="replica-%s-%d-g%d"
            % (self.name, replica.index, replica.generation))

    def _register_ledger(self, replica: _Replica, instance) -> None:
        """Attributes a fresh per-replica executable's device arrays
        to this model in the HBM ledger (``replica:<index>`` row).
        Replicas sharing the base executable register nothing — the
        load-time ``weights`` row already covers that memory.

        A mesh slice books per-participating-device rows instead
        (``slice:<index>:<device>``), leased from the HBM allocator
        under every member device's arbitration mutex — slice-unit
        admission AND truthful ``tpu_hbm_model_bytes`` under tp>1. An
        allocator refusal (RESOURCE_EXHAUSTED after eviction)
        propagates: the slice does not fit, and pretending otherwise
        would un-do PR-18's honest admission."""
        if instance is self.base:
            return
        if replica.mesh_slice is not None:
            replica.slice_res = mesh_mod.admit_slice(
                self.name, replica.mesh_slice, instance)
            return
        try:
            ledger = devstats_mod.get().ledger
            replica.ledger_row = ledger.register(
                self.name, "replica:%d" % replica.index,
                devstats_mod.model_array_bytes(instance))
        except Exception:  # noqa: BLE001 — accounting must never
            pass  # block serving

    def _release_resources(self, replica: _Replica) -> None:
        """Returns everything a replica's executable holds: its ledger
        row and — for a mesh slice — the per-device HBM leases. Both
        releases are idempotent; callers run this whenever a replica's
        instance leaves routing (stop, drain, re-initialization,
        rejected scale-up prospect)."""
        devstats_mod.get().ledger.release(replica.ledger_row)
        replica.ledger_row = None
        slice_res = replica.slice_res
        replica.slice_res = None
        if slice_res is not None:
            slice_res.release()

    def stop(self) -> None:
        """Drain for unload/shutdown: stop the supervisor, then shut
        the device queues down after their in-flight executions
        finish (hung queues are abandoned, not joined)."""
        with self._lock:
            self._stopping = True
            replicas = list(self.replicas)
        self._stop.set()
        self._supervisor.join(timeout=5)
        for replica in replicas:
            self._release_resources(replica)
            executor = replica.executor
            if executor is not None:
                # A hung replica's worker can never finish: wait only
                # for healthy queues, abandon the rest.
                executor.shutdown(wait=not replica.hung)

    # -- dynamic resize (autoscale controller) ---------------------------

    def scale_up(self) -> bool:
        """Admits ONE new replica — but only after it proves itself.
        The fresh executable is built and warmed off the routing path,
        then canaried through the full chaos-injected execution path
        (the same probe the supervisor's readmission flow runs), and
        only a passing canary enters routing. A sick birth (chaos
        targeting the new index, a poisoned factory) costs nothing but
        the probe: serving traffic never sees the replica."""
        with self._lock:
            if self._stopping:
                return False
            index = self._next_index
            self._next_index += 1
        mesh_slice = self._plan_slice(index)
        instance = self._new_instance(mesh_slice)  # warmed pre-routing
        replica = _Replica(index, instance, CircuitBreaker(
            failure_threshold=self._failure_threshold,
            reset_timeout_s=self._recovery_s), mesh_slice=mesh_slice)
        self._seed_devices(replica)
        self._start_queue(replica)
        try:
            self._register_ledger(replica, instance)
        except InferenceServerException as e:
            # Slice-unit admission refused by a member device's HBM
            # arbitration: the resize loses honestly, like a failed
            # canary — nothing entered routing, nothing leaked.
            replica.executor.shutdown(wait=False)
            with self._lock:
                self.canary_rejects += 1
            self._notify("scale_up_admission_rejected replica=%d"
                         % index)
            _LOG.warning("replica %s:%d rejected by scale-up slice "
                         "admission: %s", self.name, index, e)
            return False
        with self._lock:
            self.probes += 1
        try:
            future = replica.executor.submit(
                self._run_on, replica, self._canary_inputs(), {})
            future.result(timeout=self._watchdog_s)
            ok = True
        except Exception:  # noqa: BLE001 — any canary failure = reject
            ok = False
        admitted = False
        if ok:
            with self._lock:
                if not self._stopping:
                    self.replicas.append(replica)
                    self.count = len(self.replicas)
                    self.scale_ups += 1
                    admitted = True
        if admitted:
            self._notify("scale_up replica=%d" % index)
            _LOG.info("replica %s:%d admitted by scale-up (canary "
                      "passed)", self.name, index)
            return True
        # Rejected (or lost the race with stop()): tear the prospect
        # down completely — queue, ledger rows, slice leases, and all.
        self._release_resources(replica)
        replica.executor.shutdown(wait=False)
        if not ok:
            with self._lock:
                self.canary_rejects += 1
            self._notify("scale_up_canary_rejected replica=%d" % index)
            _LOG.warning("replica %s:%d rejected by scale-up canary — "
                         "kept out of rotation", self.name, index)
        return False

    def scale_down(self, drain_timeout_s: float = 5.0) -> bool:
        """Drains ONE replica out through the routing tail: the victim
        (an already-unhealthy replica if any — shedding a sick domain
        is free — else the newest) leaves routing immediately, its
        sticky pins release so sequences re-pin, in-flight executions
        finish normally, and only then do its device queue and ledger
        row die. Refuses to drain the last replica (that is the
        model-level scale-to-zero path, owned by the controller)."""
        with self._lock:
            if self._stopping or len(self.replicas) <= 1:
                return False
            victim = next((r for r in reversed(self.replicas)
                           if not r.healthy()), None)
            if victim is None:
                victim = max(self.replicas, key=lambda r: r.index)
            self.replicas.remove(victim)
            self.count = len(self.replicas)
            self.scale_downs += 1
            for key in [k for k, idx in self._sticky.items()
                        if idx == victim.index]:
                del self._sticky[key]
        # Bounded drain OUTSIDE the lock: waiters already executing on
        # the victim get their results; nothing new routes to it.
        deadline = time.monotonic() + max(drain_timeout_s, 0.0)
        while time.monotonic() < deadline:
            with self._lock:
                busy = victim.outstanding
            if busy <= 0:
                break
            time.sleep(0.01)
        self._release_resources(victim)
        executor = victim.executor
        if executor is not None:
            executor.shutdown(wait=not victim.hung)
        self._notify("scale_down replica=%d" % victim.index)
        _LOG.info("replica %s:%d drained out by scale-down",
                  self.name, victim.index)
        return True

    # -- routing ---------------------------------------------------------

    @staticmethod
    def _score(replica: _Replica) -> float:
        """Least expected completion time — the EndpointPool routing
        math, in-process: queue depth x per-execution latency, so a
        degraded-but-alive replica sheds work before it fails any."""
        return (replica.outstanding + 1) * max(replica.ewma_latency_s, 1e-6)

    def _pick(self, exclude=(), sticky_key=None) -> _Replica:
        """Routes one execution (raises UNAVAILABLE when every replica
        is ejected). Sticky keys pin to their replica while it stays
        healthy; an ejected pin is re-routed (and re-pinned) to the
        best healthy sibling."""
        with self._lock:
            if self._stopping:
                raise status_map.retryable_error(
                    "model '%s' is draining its replicas" % self.name,
                    retry_after_s=1.0)
            if sticky_key is not None:
                pinned = self._sticky.get(sticky_key)
                if pinned is not None and pinned not in exclude:
                    replica = next((r for r in self.replicas
                                    if r.index == pinned), None)
                    if replica is not None and replica.healthy():
                        return replica
            candidates = [r for r in self.replicas
                          if r.index not in exclude and r.healthy()]
            if not candidates:
                # Retry-After: the supervisor re-inits + canaries an
                # ejected replica each breaker rest period, so that IS
                # the honest earliest-recovery estimate.
                raise status_map.retryable_error(
                    "no healthy replica for model '%s' (%d of %d "
                    "ejected%s)"
                    % (self.name,
                       sum(1 for r in self.replicas if not r.healthy()),
                       self.count,
                       ", %d excluded" % len(exclude) if exclude else ""),
                    retry_after_s=max(self._recovery_s, 0.05))
            self._route_count += 1
            if self._route_count % EXPLORE_EVERY == 0:
                replica = candidates[
                    (self._route_count // EXPLORE_EVERY)
                    % len(candidates)]
            else:
                replica = min(candidates, key=self._score)
            if sticky_key is not None:
                self._sticky[sticky_key] = replica.index
            return replica

    def release_sticky(self, sticky_key) -> None:
        with self._lock:
            self._sticky.pop(sticky_key, None)

    def sticky_replica(self, sticky_key) -> Optional[int]:
        with self._lock:
            return self._sticky.get(sticky_key)

    # -- execution -------------------------------------------------------

    def infer(self, inputs, parameters: Optional[dict] = None,
              sticky_key=None) -> Dict[str, np.ndarray]:
        """Routes one execution (a request or a fused batch) to the
        best healthy replica; on failure, re-dispatches to a healthy
        sibling exactly once. Sequence-correlated requests derive a
        sticky key from their ``sequence_id`` parameter when the
        caller didn't pass one explicitly."""
        if sticky_key is None and parameters:
            sticky_key = parameters.get("sequence_id") or None
        replica = self._pick(sticky_key=sticky_key)
        try:
            outputs = self._execute(replica, inputs, parameters)
        except InferenceServerException as first:
            if (first.status() or "") in CLIENT_ERROR_STATUSES:
                raise  # deterministic: a sibling fails it identically
            if sticky_key is not None and replica.healthy():
                # A TRANSIENT fault on a still-healthy pinned replica
                # must not fail over: the sequence's replica-local
                # implicit state lives on this replica, and a sibling
                # would silently run stateless (wrong results, not an
                # error). Surface the fault instead — the client's
                # retry re-routes to the same healthy pin. Ejected
                # pins still re-dispatch + re-pin below (state loss is
                # inherent to losing the fault domain).
                raise
            try:
                sibling = self._pick(exclude={replica.index},
                                     sticky_key=sticky_key)
            except InferenceServerException:
                raise first
            with self._lock:
                self.redispatches += 1
            _LOG.debug("re-dispatching batch for '%s' from replica %d "
                       "to %d: %s", self.name, replica.index,
                       sibling.index, first)
            outputs = self._execute(sibling, inputs, parameters)
        # Mirror EndpointPool stickiness lifecycle: the pin is held for
        # the sequence's lifetime and released on its final step so a
        # long-lived server doesn't accrete dead pins.
        if sticky_key is not None and parameters \
                and parameters.get("sequence_end"):
            self.release_sticky(sticky_key)
        return outputs

    def _run_on(self, replica: _Replica, inputs,
                parameters: Optional[dict]):
        """Body of one device-queue execution. Chaos injection runs
        HERE — inside the fault domain — so replica-targeted faults
        (``replica=model:index``, ``hang_ms``) degrade exactly one
        replica; request-level faults stay at the core's inject."""
        chaos.inject(self.name,
                     scope=self._scope_fn() if self._scope_fn else None,
                     replica_id="%s:%d" % (self.name, replica.index),
                     device_ids=replica.device_ids or None)
        # Compile attribution runs HERE — on the replica's own device-
        # queue thread — because thread-local scopes pushed by the
        # batcher or the core do not cross the executor hand-off.
        devstats = devstats_mod.get()
        if not devstats.enabled:  # A/B off arm: zero devstats cost
            return replica.model.infer(inputs, parameters)
        with devstats.compile_scope(
                self.name, devstats_mod.shape_fingerprint(inputs)):
            return replica.model.infer(inputs, parameters)

    def _execute(self, replica: _Replica, inputs,
                 parameters: Optional[dict]) -> Dict[str, np.ndarray]:
        with self._lock:
            # The watchdog budget covers THIS execution plus everything
            # already queued ahead of it on the replica's single-thread
            # device queue: a loaded-but-healthy replica gets one
            # watchdog period per queued predecessor, so sustained load
            # can never masquerade as a hang — while a genuinely hung
            # replica still trips its FIRST waiter after exactly one
            # period.
            queued_ahead = replica.outstanding
            replica.outstanding += 1
            replica.requests += 1
            executor = replica.executor
        t0 = time.monotonic_ns()
        try:
            future = executor.submit(self._run_on, replica, inputs,
                                     parameters)
        except RuntimeError:  # queue torn down by a concurrent heal
            with self._lock:
                replica.outstanding = max(replica.outstanding - 1, 0)
            raise status_map.retryable_error(
                "replica %s:%d is re-initializing"
                % (self.name, replica.index),
                retry_after_s=max(self._recovery_s / 2.0, 0.05))
        try:
            outputs = future.result(
                timeout=self._watchdog_s * (queued_ahead + 1))
        except FuturesTimeout:
            self._mark_hung(replica)
            raise status_map.retryable_error(
                "replica %s:%d blew its %dms execution watchdog "
                "(marked unhealthy)"
                % (self.name, replica.index,
                   int(self._watchdog_s * 1000)),
                retry_after_s=max(self._watchdog_s, 0.05))
        except BaseException as e:
            self._note_failure(replica, e)
            if isinstance(e, InferenceServerException):
                raise
            raise InferenceServerException(
                "replica %s:%d execution failed: %s"
                % (self.name, replica.index, e), status="INTERNAL")
        latency_ns = time.monotonic_ns() - t0
        self._note_success(replica, latency_ns)
        return outputs

    # -- health bookkeeping ----------------------------------------------

    def _note_success(self, replica: _Replica, latency_ns: int) -> None:
        replica.breaker.record_success()
        with self._lock:
            replica.outstanding = max(replica.outstanding - 1, 0)
            replica.execution_count += 1
            replica.exec_ns += latency_ns
            latency_s = latency_ns / 1e9
            replica.ewma_latency_s = (
                latency_s if replica.ewma_latency_s == 0.0
                else 0.2 * latency_s + 0.8 * replica.ewma_latency_s)
        # Busy time routed per replica device (outside the set's lock;
        # the devstats layer does its own cheap synchronization). A
        # sharded call occupies EVERY slice member for the wall time —
        # each device gets the full duration, not a 1/width share.
        devstats = devstats_mod.get()
        if replica.mesh_slice is not None:
            for device_key in replica.device_keys:
                devstats.record_busy(device_key, latency_ns)
        else:
            devstats.replica_busy(replica.index, latency_ns)

    def _notify(self, label: str) -> None:
        """Fires the lifecycle event hook (never under the set's
        lock; forensics must never affect serving)."""
        if self._event_hook is None:
            return
        try:
            self._event_hook(self.name, label)
        except Exception:  # noqa: BLE001 — stamping is advisory
            pass

    def _note_failure(self, replica: _Replica,
                      error: BaseException) -> None:
        from client_tpu.robust import _breaker_resolve

        was_healthy = replica.healthy()
        _breaker_resolve(replica.breaker, error)
        ejected = False
        with self._lock:
            replica.outstanding = max(replica.outstanding - 1, 0)
            replica.failures += 1
            for device_key in replica.device_keys:
                self._device_evidence[device_key] = \
                    self._device_evidence.get(device_key, 0) + 1
            if was_healthy and not replica.healthy():
                replica.ejected_count += 1
                self.ejections += 1
                ejected = True
                _LOG.warning("replica %s:%d ejected (breaker open "
                             "after repeated execution failures)",
                             self.name, replica.index)
        if ejected:
            self._notify(self._eject_label("breaker_trip", replica))

    def _eject_label(self, kind: str, replica: _Replica) -> str:
        """Incident label for an ejection: a slice's label names every
        member chip — the fault domain IS the device set, and the
        flight-recorder trail must say which chips left serving."""
        label = "%s replica=%d" % (kind, replica.index)
        if replica.mesh_slice is not None:
            label += " devices=%s" % (",".join(
                str(d) for d in replica.device_ids))
        return label

    def _mark_hung(self, replica: _Replica) -> None:
        replica.breaker.record_failure()  # availability evidence too
        ejected = False
        with self._lock:
            replica.outstanding = max(replica.outstanding - 1, 0)
            replica.failures += 1
            self.watchdog_trips += 1
            for device_key in replica.device_keys:
                self._device_evidence[device_key] = \
                    self._device_evidence.get(device_key, 0) + 1
            if not replica.hung:
                replica.hung = True
                replica.ejected_count += 1
                self.ejections += 1
                ejected = True
                _LOG.warning("replica %s:%d marked unhealthy "
                             "(watchdog)", self.name, replica.index)
        if ejected:
            self._notify(self._eject_label("watchdog_trip", replica))

    # -- supervisor (self-healing) ---------------------------------------

    def _supervise(self) -> None:
        interval = max(min(self._recovery_s / 2.0, 0.5), 0.05)
        while not self._stop.wait(interval):
            with self._lock:
                fleet = list(self.replicas)
            for replica in fleet:
                if self._stop.is_set():
                    return
                if replica.healthy():
                    continue
                # Respect the breaker's rest period whether or not the
                # replica is hung: probing (and rebuilding) faster than
                # the recovery pace gathers no new evidence. A hung
                # replica whose breaker is still CLOSED (first watchdog
                # trip) probes immediately.
                if replica.breaker.state != CircuitBreaker.CLOSED \
                        and not replica.breaker.admits():
                    continue
                self._heal(replica)

    def _heal(self, replica: _Replica) -> None:
        """Re-initialize + canary-probe one unhealthy replica. The
        half-open probe slot is claimed FIRST so a resting breaker
        never costs a factory re-instantiation per supervisor tick;
        the fresh executable is then built BEFORE the probe so a
        poisoned weight state cannot pass the canary, and the canary
        runs through the full execution path (chaos included) so a
        replica whose fault is still active stays ejected."""
        breaker = replica.breaker
        if breaker.state != CircuitBreaker.CLOSED:
            try:
                breaker.before_call()  # claim the half-open probe slot
            except InferenceServerException:
                return
        self._reinitialize(replica)
        with self._lock:
            self.probes += 1
        try:
            future = replica.executor.submit(
                self._run_on, replica, self._canary_inputs(), {})
            future.result(timeout=self._watchdog_s)
            ok = True
        except Exception:  # noqa: BLE001 — any canary failure = not yet
            ok = False
        if ok:
            breaker.record_success()
            with self._lock:
                replica.hung = False
                replica.readmitted_count += 1
                self.readmissions += 1
            _LOG.warning("replica %s:%d readmitted (canary passed "
                         "after re-initialization)", self.name,
                         replica.index)
        else:
            breaker.record_failure()

    def _reinitialize(self, replica: _Replica) -> None:
        """Fresh executable + weights on a fresh device-queue thread.
        The old executor is abandoned (shutdown without waiting): a
        hung worker can never be joined, and any work still queued on
        it either finishes into the void or times out at its waiter's
        watchdog and re-dispatches."""
        old = replica.executor
        # Same slice, fresh executable: the device block is the
        # replica's identity, so re-initialization rebuilds the
        # sharded program over the SAME member devices.
        instance = self._new_instance(replica.mesh_slice)
        # The old executable's ledger rows/leases die with it; the
        # fresh instance registers its own (re-init is an allocation
        # site — skipping it here would leak a row per heal cycle).
        self._release_resources(replica)
        try:
            self._register_ledger(replica, instance)
        except InferenceServerException as e:
            # Slice re-admission refused (another model grew into the
            # freed budget): serve anyway — the weights are already
            # resident — but log the accounting gap; the next heal
            # cycle retries the booking.
            _LOG.warning("replica %s:%d re-admission lease refused "
                         "(%s); slice accounting degraded until the "
                         "next heal", self.name, replica.index, e)
        with self._lock:
            replica.model = instance
            self._start_queue(replica)
        if old is not None:
            old.shutdown(wait=False)

    def _canary_inputs(self) -> Dict[str, np.ndarray]:
        """Zero-valued inputs matching the model's declared signature
        (batch 1; variable dims collapse to 1; BYTES rows get empty
        payloads). Models with exotic signatures can override via a
        ``make_canary_inputs()`` method."""
        maker = getattr(self.base, "make_canary_inputs", None)
        if callable(maker):
            return maker()
        inputs: Dict[str, np.ndarray] = {}
        batched = int(getattr(self.base, "max_batch_size", 0)) > 0
        for spec in self.base.inputs:
            if getattr(spec, "optional", False):
                continue
            shape = [1 if int(d) < 0 else int(d) for d in spec.shape]
            if batched:
                shape = [1] + shape
            if spec.datatype == "BYTES":
                inputs[spec.name] = np.full(shape, b"", dtype=object)
            else:
                inputs[spec.name] = np.zeros(
                    shape, dtype=triton_to_np_dtype(spec.datatype))
        return inputs

    # -- observability ----------------------------------------------------

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.healthy())

    def snapshot(self) -> dict:
        """Point-in-time health + cumulative counters (feeds the
        ModelStatistics replica rows and the tpu_replica_* Prometheus
        families)."""
        with self._lock:
            replicas = [
                {
                    "index": r.index,
                    "healthy": r.healthy(),
                    "hung": r.hung,
                    "breaker": r.breaker.state,
                    "outstanding": r.outstanding,
                    "ewma_latency_ms": round(r.ewma_latency_s * 1000.0, 3),
                    "requests": r.requests,
                    "failures": r.failures,
                    "execution_count": r.execution_count,
                    "exec_ns": r.exec_ns,
                    "ejected_count": r.ejected_count,
                    "readmitted_count": r.readmitted_count,
                    "devices": list(r.device_ids),
                }
                for r in self.replicas
            ]
            return {
                "count": self.count,
                "healthy": sum(1 for r in self.replicas if r.healthy()),
                "sharded": self.sharded,
                "slice_width": self.slice_width,
                "device_evidence": dict(self._device_evidence),
                "ejections": self.ejections,
                "readmissions": self.readmissions,
                "redispatches": self.redispatches,
                "watchdog_trips": self.watchdog_trips,
                "probes": self.probes,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "canary_rejects": self.canary_rejects,
                "replicas": replicas,
            }
