"""gRPC front-end for the inference server core."""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

import grpc

from client_tpu import status_map
from client_tpu.server import cancel as cancel_mod
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import (
    GRPCInferenceServiceServicer,
    add_GRPCInferenceServiceServicer_to_server,
)
from client_tpu.server.core import (
    InferenceServerCore,
    mint_request_id,
    stream_error_response,
)
from client_tpu.utils import InferenceServerException


def _trace_context(context) -> Optional[str]:
    """W3C traceparent from the call's invocation metadata (the gRPC
    twin of the HTTP header), or None — malformed/absent context must
    never fail a request."""
    try:
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                return value
    except Exception:  # noqa: BLE001 — propagation is best-effort
        pass
    return None


def _abort(context, error: InferenceServerException):
    code = status_map.grpc_code(error.status())
    if status_map.is_retryable_status(error.status()):
        # The gRPC twin of the HTTP Retry-After header: a trailing
        # metadata hint that well-behaved clients (RetryPolicy) use as
        # their minimum backoff before retrying a shed request.
        # Quota rejects (RESOURCE_EXHAUSTED) carry the token-bucket
        # refill time; queue rejects carry the server's estimate.
        retry_after = getattr(error, "retry_after_s", None)
        try:
            context.set_trailing_metadata((
                ("retry-after",
                 "%.3f" % retry_after if retry_after else "1"),))
        except Exception:  # noqa: BLE001 — the abort must still fire
            pass
    context.abort(code, error.message())


def _apply_tenant_metadata(request, context) -> None:
    """Maps a `tenant` invocation-metadata key onto the request's
    `tenant` parameter (the transport-neutral identity quotas key on);
    an in-request parameter wins over metadata."""
    if "tenant" in request.parameters:
        return
    try:
        for key, value in context.invocation_metadata() or ():
            if key == "tenant" and value:
                request.parameters["tenant"].string_param = value
                return
    except Exception:  # noqa: BLE001 — identity is best-effort
        pass


class _StreamDispatcher:
    """Transport-neutral guts of ``ModelStreamInfer``: a bounded output
    queue fed by a worker pool dispatching pipelined requests
    (same-sequence requests chained in arrival order), plus an explicit
    teardown signal both front-ends raise when the client goes away —
    the sync handler from its generator ``finally``, the aio handler
    from its ``CancelledError``. Workers observe teardown via the
    bounded put loop, cancel their request tokens, and close their
    per-request generators, so abandonment handling is identical on
    both transports."""

    # Bounded: the old sequential `yield from` backpressured through
    # HTTP/2 flow control; with threaded dispatch a non-reading client
    # must hit this cap (workers block in put) instead of growing
    # server memory without bound.
    QUEUE_DEPTH = 64

    def __init__(self, core: InferenceServerCore, context,
                 workers: int = 8):
        import queue as _queue
        from concurrent.futures import ThreadPoolExecutor

        self._core = core
        self._queue_mod = _queue
        self._out: _queue.Queue = _queue.Queue(maxsize=self.QUEUE_DEPTH)
        self.sentinel = object()
        self._cancelled = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="stream-infer")
        # key -> tail future of that correlation id's chain. An entry
        # is dropped as soon as its tail future completes while still
        # being the tail (sequence ended, errored, or simply idle) —
        # before this a long-lived stream kept one future alive per
        # correlation id it ever saw.
        self._sequence_tail: dict = {}
        self._tail_lock = threading.Lock()
        # One traceparent per stream (gRPC metadata is per-call):
        # every request pipelined on this stream joins that trace.
        self._trace_context = _trace_context(context)
        # Likewise one tenant identity per stream: without this the
        # streaming RPC would bypass tenant quotas entirely.
        self._tenant = None
        try:
            for key, value in context.invocation_metadata() or ():
                if key == "tenant" and value:
                    self._tenant = value
                    break
        except Exception:  # noqa: BLE001 — identity is best-effort
            pass

    def put_out(self, item) -> bool:
        while not self._cancelled.is_set():
            try:
                self._out.put(item, timeout=0.5)
                return True
            except self._queue_mod.Full:
                continue
        return False

    def get_out(self):
        """Blocking take for the sync front-end: the reader thread's
        sentinel always arrives."""
        return self._out.get()

    def poll_out(self):
        """Bounded take for the aio front-end's executor reads: once
        teardown is signalled and the queue has drained this returns
        the sentinel, so an abandoned read always lets its pool thread
        go."""
        while True:
            try:
                return self._out.get(timeout=0.25)
            except self._queue_mod.Empty:
                if self._cancelled.is_set():
                    return self.sentinel

    def put_sentinel(self) -> None:
        self.put_out(self.sentinel)

    def wait_all(self) -> None:
        """End-of-requests barrier: waits for every in-flight
        request."""
        self._pool.shutdown(wait=True)

    def shutdown(self) -> None:
        self._cancelled.set()
        self._pool.shutdown(wait=False)

    def dispatch(self, request) -> None:
        if self._cancelled.is_set():
            return
        key = None
        param = request.parameters.get("sequence_id")
        if param is not None:
            key = param.int64_param or param.string_param or None
        try:
            if key:
                with self._tail_lock:
                    prev = self._sequence_tail.get(key)
                    future = self._pool.submit(self._run_after, prev,
                                               request)
                    self._sequence_tail[key] = future
                self._drop_when_tail(key, future)
            else:
                self._pool.submit(self._run_one, request)
        except RuntimeError:
            # pool shut down: teardown raced an in-flight dispatch
            if not self._cancelled.is_set():
                raise

    def _drop_when_tail(self, key, future) -> None:
        def _done(f):
            with self._tail_lock:
                if self._sequence_tail.get(key) is f:
                    del self._sequence_tail[key]

        future.add_done_callback(_done)

    def _run_after(self, prev, request) -> None:
        # Same-sequence requests must reach the sequence scheduler in
        # arrival order (it serializes execution, but ordering of
        # ticket issue is the transport's to preserve) — so each
        # chains on its predecessor; distinct sequences still run
        # concurrently.
        if prev is not None:
            try:
                prev.result()
            except Exception:  # noqa: BLE001 — order, not success
                pass
        self._run_one(request)

    def _run_one(self, request) -> None:
        mint_request_id(request)
        if self._tenant and "tenant" not in request.parameters:
            request.parameters["tenant"].string_param = self._tenant
        token = (self._core.cancel.mint(request.id)
                 if self._core.cancel.enabled else None)
        generator = self._core.stream_infer(
            request, trace_context=self._trace_context, cancel=token)
        try:
            for response in generator:
                if (self._cancelled.is_set()
                        or not self.put_out(response)):
                    break
        except InferenceServerException as e:
            # decoupled errors ride the stream, not abort it
            self.put_out(stream_error_response(request, str(e)))
        except Exception as e:  # noqa: BLE001 — never kill the stream
            self.put_out(stream_error_response(
                request, "internal error: %s" % e))
        finally:
            # Stream teardown (client went away) cancels the request
            # BEFORE closing the generator so the core's stream
            # finally sees a flipped token and books the disconnect; a
            # completed request's close is a no-op.
            if token is not None and self._cancelled.is_set():
                token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
            generator.close()


class InferenceServicer(GRPCInferenceServiceServicer):
    def __init__(self, core: InferenceServerCore):
        self._core = core

    def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=self._core.server_live())

    def ServerReady(self, request, context):
        return pb.ServerReadyResponse(ready=self._core.server_ready())

    def ModelReady(self, request, context):
        ready = self._core.model_ready(request.name, request.version)
        # Same partial-degradation metadata the HTTP ready route sends
        # as x-replica-* headers: trailing metadata so clients can
        # weight a degraded-but-ready instance-group model.
        health = self._core.replica_health(request.name)
        if health is not None:
            try:
                context.set_trailing_metadata((
                    ("replica-healthy", str(health[0])),
                    ("replica-total", str(health[1])),
                ))
            except Exception:  # noqa: BLE001 — metadata is advisory
                pass
        return pb.ModelReadyResponse(ready=ready)

    def ServerMetadata(self, request, context):
        return self._core.server_metadata()

    def ModelMetadata(self, request, context):
        try:
            return self._core.model_metadata(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)

    def ModelConfig(self, request, context):
        try:
            return self._core.model_config(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)

    def ModelInfer(self, request, context):
        mint_request_id(request)
        _apply_tenant_metadata(request, context)
        token = None
        if self._core.cancel.enabled:
            token = self._core.cancel.mint(request.id)
            try:
                # Fires on RPC termination: a client-side cancel or
                # dropped channel flips the token mid-flight; after a
                # normal completion the flip is a harmless no-op (the
                # token is already untracked and nobody reads it).
                context.add_callback(lambda: token.cancel(
                    cancel_mod.REASON_CLIENT_DISCONNECT))
            except Exception:  # noqa: BLE001 — detection is best-effort
                pass
        try:
            return self._core.infer(
                request, trace_context=_trace_context(context),
                cancel=token)
        except InferenceServerException as e:
            _abort(context, e)

    # In-flight requests per stream. Triton decoupled-stream
    # semantics: a client may pipeline many requests on one stream and
    # responses interleave (matched by request id) — handling them one
    # at a time would multiply every client's latency by its in-flight
    # depth.
    STREAM_WORKERS = 8

    def ModelStreamInfer(self, request_iterator, context):
        dispatcher = _StreamDispatcher(self._core, context,
                                       workers=self.STREAM_WORKERS)

        def reader():
            try:
                for request in request_iterator:
                    dispatcher.dispatch(request)
                dispatcher.wait_all()
            finally:
                dispatcher.put_sentinel()  # no-op when the client is gone

        reader_thread = threading.Thread(target=reader, daemon=True,
                                         name="stream-infer-reader")
        reader_thread.start()
        try:
            while True:
                item = dispatcher.get_out()
                if item is dispatcher.sentinel:
                    return
                yield item
        finally:
            # Stream teardown (client went away: gRPC closes this
            # generator): workers observe the signal, cancel their
            # request tokens, and close their per-request generators
            # so model-side abandonment handling (GeneratorExit ->
            # request.cancelled, e.g. the LLM's lane reclaim) still
            # fires with threaded dispatch.
            dispatcher.shutdown()

    def ModelStatistics(self, request, context):
        try:
            return self._core.model_statistics(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)

    def RepositoryIndex(self, request, context):
        return self._core.repository_index(request.ready)

    def RepositoryModelLoad(self, request, context):
        try:
            self._core.load_model(request.model_name)
            return pb.RepositoryModelLoadResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def RepositoryModelUnload(self, request, context):
        try:
            self._core.unload_model(request.model_name)
            return pb.RepositoryModelUnloadResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def SystemSharedMemoryStatus(self, request, context):
        return self._core.system_shm_status(request.name)

    def SystemSharedMemoryRegister(self, request, context):
        try:
            self._core.register_system_shm(
                request.name, request.key, request.offset, request.byte_size
            )
            return pb.SystemSharedMemoryRegisterResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def SystemSharedMemoryUnregister(self, request, context):
        try:
            self._core.unregister_system_shm(request.name)
            return pb.SystemSharedMemoryUnregisterResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def TpuSharedMemoryStatus(self, request, context):
        return self._core.tpu_shm_status(request.name)

    def TpuSharedMemoryRegister(self, request, context):
        try:
            self._core.register_tpu_shm(
                request.name, request.raw_handle, request.device_id,
                request.byte_size,
            )
            return pb.TpuSharedMemoryRegisterResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def TpuSharedMemoryUnregister(self, request, context):
        try:
            self._core.unregister_tpu_shm(request.name)
            return pb.TpuSharedMemoryUnregisterResponse()
        except InferenceServerException as e:
            _abort(context, e)

    def TraceSetting(self, request, context):
        updates = {k: list(v.value) for k, v in request.settings.items()}
        settings = self._core.trace_setting(request.model_name, updates)
        response = pb.TraceSettingResponse()
        for key, values in settings.items():
            response.settings[key].value.extend(values)
        return response

    def LogSettings(self, request, context):
        updates = {}
        for key, value in request.settings.items():
            which = value.WhichOneof("parameter_choice")
            if which:
                updates[key] = getattr(value, which)
        settings = self._core.log_settings(updates)
        response = pb.LogSettingsResponse()
        for key, value in settings.items():
            if isinstance(value, bool):
                response.settings[key].bool_param = value
            elif isinstance(value, int):
                response.settings[key].uint32_param = value
            else:
                response.settings[key].string_param = str(value)
        return response


async def _abort_aio(context, error: InferenceServerException):
    """`_abort` twin for grpc.aio handler coroutines, where
    ``context.abort`` is a coroutine (trailing metadata stays sync)."""
    code = status_map.grpc_code(error.status())
    if status_map.is_retryable_status(error.status()):
        retry_after = getattr(error, "retry_after_s", None)
        try:
            context.set_trailing_metadata((
                ("retry-after",
                 "%.3f" % retry_after if retry_after else "1"),))
        except Exception:  # noqa: BLE001 — the abort must still fire
            pass
    await context.abort(code, error.message())


class AioInferenceServicer(InferenceServicer):
    """InferenceServicer with the unary infer path rewritten as a
    coroutine for the grpc.aio front-end.

    The asyncio server's sync-migration path hands non-coroutine
    handlers a ``_SyncServicerContext`` whose ``add_callback`` accepts
    the callback and then never invokes it — not on client cancel, not
    even at normal RPC completion — so a sync ``ModelInfer`` under the
    aio server is blind to the caller going away. A coroutine handler
    gets the real signal: grpc.aio cancels the handler task when the
    RPC terminates early, and the ``CancelledError`` arm flips the
    request's token. The blocking work still runs on the migration
    pool (via ``run_in_executor``) so serving semantics and pool
    sizing are unchanged; the abandoned executor job unwinds at its
    next stage boundary once it observes the flipped token.
    """

    def __init__(self, core: InferenceServerCore, executor):
        super().__init__(core)
        self._executor = executor

    async def ModelInfer(self, request, context):
        import asyncio

        mint_request_id(request)
        _apply_tenant_metadata(request, context)
        token = (self._core.cancel.mint(request.id)
                 if self._core.cancel.enabled else None)
        trace_context = _trace_context(context)

        def _work():
            return self._core.infer(
                request, trace_context=trace_context, cancel=token)

        try:
            return await asyncio.get_running_loop().run_in_executor(
                self._executor, _work)
        except asyncio.CancelledError:
            if token is not None:
                token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
            raise
        except InferenceServerException as e:
            await _abort_aio(context, e)

    async def ModelStreamInfer(self, request_iterator, context):
        """Async-generator twin of the sync handler, for the same
        reason as ``ModelInfer``: a sync streaming generator under the
        aio server is never closed when the client goes away (its
        ``finally`` — the teardown signal — simply does not run, so
        workers wedge in the bounded put loop and tokens never flip).
        grpc.aio DOES close an async generator on RPC termination, so
        teardown rides this coroutine's ``finally`` instead. The
        blocking dispatch machinery is the shared
        ``_StreamDispatcher``; queue reads hop through the migration
        pool to keep the event loop unblocked."""
        import asyncio

        dispatcher = _StreamDispatcher(self._core, context,
                                       workers=self.STREAM_WORKERS)
        loop = asyncio.get_running_loop()

        async def reader():
            try:
                async for request in request_iterator:
                    dispatcher.dispatch(request)
                await loop.run_in_executor(self._executor,
                                           dispatcher.wait_all)
            finally:
                # Off-loop: the sentinel put can block behind a slow
                # reader (bounded queue); no-op when the client is
                # gone.
                self._executor.submit(dispatcher.put_sentinel)

        reader_task = asyncio.ensure_future(reader())
        try:
            while True:
                item = await loop.run_in_executor(self._executor,
                                                  dispatcher.poll_out)
                if item is dispatcher.sentinel:
                    return
                yield item
        finally:
            dispatcher.shutdown()
            reader_task.cancel()


def debug_generic_handler(core: InferenceServerCore):
    """The gRPC surface of ``GET /v2/debug`` — a *generic* (descriptor-
    free) service, so no protoc run is needed for a JSON diagnostic
    payload. Two unary methods, each taking an optional JSON request
    body (``{"model": "M"}``) and returning UTF-8 JSON bytes:

    * ``/inference.Debug/Snapshot`` — ``core.debug_snapshot()``;
    * ``/inference.Debug/Flight`` — ``core.debug_flight()`` (the
      flight-ring anomaly-trace dump);
    * ``/inference.Debug/Profile`` — ``core.debug_profile()``
      (on-demand bounded profiler capture; request body
      ``{"duration_ms": N, "model": "M"}``, both optional).

    Call from any grpc channel:
    ``channel.unary_unary("/inference.Debug/Snapshot",
    request_serializer=None, response_deserializer=None)(b"{}")``."""
    import json

    def _model_of(request_bytes: bytes) -> str:
        if not request_bytes:
            return ""
        try:
            doc = json.loads(request_bytes.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return ""
        return str(doc.get("model") or "")

    def snapshot(request_bytes, context):
        return json.dumps(core.debug_snapshot(_model_of(request_bytes)),
                          default=str).encode("utf-8")

    def flight(request_bytes, context):
        return json.dumps(core.debug_flight(_model_of(request_bytes)),
                          default=str).encode("utf-8")

    def profile(request_bytes, context):
        doc = {}
        if request_bytes:
            try:
                doc = json.loads(request_bytes.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                doc = {}
        if not isinstance(doc, dict):
            doc = {}
        try:
            duration_ms = int(doc.get("duration_ms") or 500)
        except (TypeError, ValueError):
            duration_ms = 500
        # Blocks this handler thread for the (clamped) capture window;
        # concurrent callers coalesce single-flight inside the core.
        return json.dumps(
            core.debug_profile(duration_ms, str(doc.get("model") or "")),
            default=str).encode("utf-8")

    def identity(payload: bytes) -> bytes:
        return payload

    return grpc.method_handlers_generic_handler(
        "inference.Debug",
        {
            "Snapshot": grpc.unary_unary_rpc_method_handler(
                snapshot, request_deserializer=identity,
                response_serializer=identity),
            "Flight": grpc.unary_unary_rpc_method_handler(
                flight, request_deserializer=identity,
                response_serializer=identity),
            "Profile": grpc.unary_unary_rpc_method_handler(
                profile, request_deserializer=identity,
                response_serializer=identity),
        })


_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", -1),
    ("grpc.max_receive_message_length", -1),
]


def build_grpc_server(
    core: InferenceServerCore,
    address: Optional[str] = "0.0.0.0:8001",
    max_workers: int = 16,
    extra_servicers=(),
) -> grpc.Server:
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=list(_CHANNEL_OPTIONS),
    )
    add_GRPCInferenceServiceServicer_to_server(InferenceServicer(core), server)
    server.add_generic_rpc_handlers((debug_generic_handler(core),))
    for add_fn, servicer in extra_servicers:
        add_fn(servicer, server)
    if address:
        server.add_insecure_port(address)
    return server


class AioGrpcServerThread:
    """A ``grpc.aio`` server driven by a dedicated event-loop thread.

    The asyncio C-core transport clears ~1.8x the unary request rate of
    the thread-pool sync server on this image (the sync server tops out
    ~1.1k `simple` infer/s; asyncio polling lifts the same servicer to
    ~1.9k against the native harness), so the serving entry points use
    this by default.  The sync ``InferenceServicer`` is reused verbatim:
    grpcio executes non-coroutine handlers (including sync streaming
    generators) on its executor, so serving semantics are identical.
    """

    def __init__(self, core: InferenceServerCore, address: str,
                 extra_servicers=(), max_workers: int = 96,
                 on_bound=None):
        # The servicer's handlers are sync and BLOCK in the migration
        # pool (dynamic-batcher waits ride a threading.Event; a
        # batched round trip is ~80 ms behind the relay) — at 64+
        # concurrent requests a 16-thread pool serves them in waves
        # and the wave count multiplies client latency. Blocked
        # threads are cheap; size the pool past the serving
        # concurrency the bench drives.
        import asyncio
        import threading

        self._loop = asyncio.new_event_loop()
        self._server = None
        self._stop_event = None
        self._grace = 1.0
        self.port = 0
        started = threading.Event()
        error: list = []

        async def _serve():
            try:
                pool = futures.ThreadPoolExecutor(
                    max_workers=max_workers)
                server = grpc.aio.server(
                    migration_thread_pool=pool,
                    options=list(_CHANNEL_OPTIONS))
                # Coroutine ModelInfer + sync everything-else; the
                # same pool backs both the migration path and the
                # coroutine's run_in_executor dispatch.
                add_GRPCInferenceServiceServicer_to_server(
                    AioInferenceServicer(core, pool), server)
                server.add_generic_rpc_handlers(
                    (debug_generic_handler(core),))
                for add_fn, servicer in extra_servicers:
                    add_fn(servicer, server)
                self.port = server.add_insecure_port(address)
                if self.port == 0:
                    raise RuntimeError("unable to bind %s" % address)
                if on_bound is not None:
                    # Post-bind, pre-serve: state that must be visible
                    # to the very first request (e.g. the arena's
                    # public_url, which stamps every minted handle).
                    on_bound(self.port)
                await server.start()
            except Exception as exc:  # surface bind/setup errors to caller
                error.append(exc)
                started.set()
                return
            self._server = server
            self._stop_event = asyncio.Event()
            started.set()
            # Shutdown runs in THIS task once stop() sets the event —
            # grpc.aio's stop() never completes when it races a
            # pending wait_for_termination() on the same server (it
            # hung for the full timeout even on an idle server).
            await self._stop_event.wait()
            await server.stop(self._grace)

        def _run():
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(_serve())
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="grpc-aio-server")
        self._thread.start()
        started_in_time = started.wait(60)
        if error:
            raise error[0]
        if not started_in_time or self._server is None:
            # A slow startup could still complete start() after we
            # raise, leaving an orphaned running server with no handle
            # to stop it — signal the serve task to shut down and join
            # the thread before surfacing the failure.
            def _abort():
                if self._stop_event is not None:
                    self._stop_event.set()
                else:
                    # start() hasn't finished: cancel everything on the
                    # loop so run_until_complete unwinds.
                    for task in asyncio.all_tasks(self._loop):
                        task.cancel()

            try:
                self._loop.call_soon_threadsafe(_abort)
            except RuntimeError:
                pass  # loop already closed — thread is done
            self._thread.join(timeout=15)
            raise RuntimeError("aio gRPC server failed to start on %s"
                               % address)

    def stop(self, grace: float = 1.0):
        import logging

        if self._server is None:
            return
        self._grace = grace
        try:
            self._loop.call_soon_threadsafe(self._stop_event.set)
        except RuntimeError as exc:  # loop already closed by a racer
            logging.getLogger(__name__).warning(
                "aio gRPC server stop signal not delivered: %s", exc)
        self._server = None
        self._thread.join(timeout=grace + 15)
        if self._thread.is_alive():
            logging.getLogger(__name__).warning(
                "aio gRPC server thread still alive after stop(); the "
                "listening port may not be released yet")
