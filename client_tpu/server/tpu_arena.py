"""Server-owned TPU HBM arena: the TPU-native shared-memory data plane.

Re-designs the reference's CUDA shared-memory model (cudaMalloc +
cudaIpcGetMemHandle + cudaIpcOpenMemHandle, utils/cuda_shared_memory/
__init__.py:107-149) for TPU reality: one process owns the device, so
"shared" regions are *named slots* in the owning process. A slot holds
a ``jax.Array``; the handle handed to clients is a signed logical
descriptor, not a pointer.

Zero-copy properties:
- input resolution hands the slot's device array to the jitted model
  unchanged (no host round-trip, no copy);
- output placement stores the result array by reference — on TPU an
  "in-place write to shared memory" is a reference swap;
- host data written by a remote client crosses host->device once at
  population time, never on the request path (matching how
  perf-harness shm mode populates regions once and reuses them).
"""

from __future__ import annotations

import json
import secrets
import threading
import uuid
from typing import Dict, Optional

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
    wire_dtype_element_size,
)


class _Segment:
    """One typed tensor (or raw byte run) living at an offset in a
    region. Regions hold disjoint segments so multi-tensor layouts
    (input_0 at 0, input_1 at 4096, ...) keep per-tensor dtype/shape
    and partial writes never round-trip the whole region."""

    __slots__ = ("offset", "nbytes", "datatype", "shape", "array")

    def __init__(self, offset: int, nbytes: int, datatype: Optional[str],
                 shape: Optional[list], array):
        self.offset = offset
        self.nbytes = nbytes
        self.datatype = datatype  # None = raw uint8 run
        self.shape = shape
        self.array = array  # jax.Array (device) or np.ndarray (BYTES)

    @property
    def end(self) -> int:
        return self.offset + self.nbytes


class _Region:
    def __init__(self, region_id: str, device, device_id: int, byte_size: int,
                 nonce: str):
        self.region_id = region_id
        self.device = device
        self.device_id = device_id
        self.byte_size = byte_size
        self.nonce = nonce
        self.lock = threading.Lock()
        # Disjoint segments sorted by offset.
        self.segments: list = []
        # Device-ledger row for this slot's logical reservation
        # (registered by create_region, released by destroy_region).
        self.ledger_row = None
        # HBM-allocator lease (docs/hbm.md): when the allocator layer
        # is importable the lease supersedes the direct ledger row —
        # it registers the same arena/regions row itself and the
        # bytes count against the managed device budget.
        self.hbm_lease = None


class TpuArena:
    """Named HBM slots on the arena's devices."""

    def __init__(self, platform: Optional[str] = None, devices=None,
                 public_url: Optional[str] = None):
        import jax

        self._jax = jax
        if devices is not None:
            # Host-local subset: in a multi-host deployment each
            # host's serving process pins its arena to ITS devices, so
            # arena traffic rides ICI only — cross-host tensor
            # movement goes through the DCN pull path
            # (docs/cross_host_arena.md), never through the arena.
            self._devices = list(devices)
        elif platform:
            self._devices = jax.devices(platform)
        else:
            self._devices = jax.devices()
        self.arena_id = uuid.uuid4().hex[:12]
        # When set, handles carry the owner's address so any other
        # host's server can redeem them via PullRegion (the handle is
        # the capability; the URL is just routing).
        self.public_url = public_url
        self._regions: Dict[str, _Region] = {}
        self._lock = threading.Lock()

    def set_public_url(self, url: str) -> None:
        self.public_url = url

    # -- lifecycle -------------------------------------------------------

    def device_for(self, device_id: int):
        if device_id < 0 or device_id >= len(self._devices):
            raise InferenceServerException(
                "device_id %d out of range (%d devices)"
                % (device_id, len(self._devices)),
                status="INVALID_ARGUMENT",
            )
        return self._devices[device_id]

    def create_region(self, byte_size: int, device_id: int = 0) -> bytes:
        """Allocate a slot; returns the serialized raw handle."""
        if byte_size <= 0:
            raise InferenceServerException(
                "byte_size must be positive", status="INVALID_ARGUMENT"
            )
        device = self.device_for(device_id)
        region_id = uuid.uuid4().hex
        nonce = secrets.token_hex(8)
        region = _Region(region_id, device, device_id, byte_size, nonce)
        # HBM attribution: arena slots are client-reserved device
        # memory nothing model-keyed would otherwise explain — one
        # aggregated `arena/regions` row covers them all (per-region
        # handles release their own contribution). The bytes flow
        # through the HBM allocator (best-effort: client reservations
        # charge the budget but never evict models), which registers
        # the ledger row itself; the direct ledger write is the
        # fallback when only devstats is importable.
        try:
            from client_tpu.server import hbm

            region.hbm_lease = hbm.get().lease(
                "arena", "regions", byte_size, best_effort=True)
        except Exception:  # noqa: BLE001 — accounting must never
            pass  # block the data plane
        if region.hbm_lease is None:
            try:
                from client_tpu.server import devstats

                ledger = devstats.get().ledger
                region.ledger_row = ledger.register("arena", "regions",
                                                    byte_size)
            except Exception:  # noqa: BLE001 — accounting must never
                pass  # block the data plane
        with self._lock:
            self._regions[region_id] = region
        return self._serialize_handle(region)

    def _serialize_handle(self, region: _Region) -> bytes:
        descriptor = {
            "arena_id": self.arena_id,
            "region_id": region.region_id,
            "device_id": region.device_id,
            "byte_size": region.byte_size,
            "nonce": region.nonce,
        }
        if self.public_url:
            descriptor["owner_url"] = self.public_url
        return json.dumps(descriptor).encode()

    def _authenticate(self, raw_handle: bytes, not_found_status: str
                      ) -> _Region:
        """Parse + authenticate a handle descriptor (arena_id, region,
        nonce) — the single capability check every redemption path
        (local registration AND cross-host pull) goes through."""
        try:
            descriptor = json.loads(raw_handle)
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise InferenceServerException(
                "malformed TPU shared memory handle",
                status="INVALID_ARGUMENT")
        region = self._regions.get(descriptor.get("region_id", ""))
        if (
            region is None
            or descriptor.get("arena_id") != self.arena_id
            or descriptor.get("nonce") != region.nonce
        ):
            raise InferenceServerException(
                "TPU shared memory handle does not match any arena region",
                status=not_found_status,
            )
        return region

    def validate_handle(self, raw_handle: bytes, device_id: int,
                        byte_size: int) -> str:
        """Check a client-provided handle against this arena; returns
        the region_id (used by TpuSharedMemoryRegister)."""
        region = self._authenticate(raw_handle, "INVALID_ARGUMENT")
        if byte_size > region.byte_size:
            raise InferenceServerException(
                "registered byte_size %d exceeds region size %d"
                % (byte_size, region.byte_size),
                status="INVALID_ARGUMENT",
            )
        if device_id != region.device_id:
            raise InferenceServerException(
                "registered device_id %d does not match region device %d"
                % (device_id, region.device_id),
                status="INVALID_ARGUMENT",
            )
        return region.region_id

    def destroy_region(self, region_id: str) -> None:
        with self._lock:
            region = self._regions.pop(region_id, None)
        if region is not None:
            region.segments = []  # drop the HBM buffer references
            try:
                from client_tpu.server import hbm

                hbm.get().release(region.hbm_lease)
            except Exception:  # noqa: BLE001
                pass
            region.hbm_lease = None
            try:
                from client_tpu.server import devstats

                devstats.get().ledger.release(region.ledger_row)
            except Exception:  # noqa: BLE001
                pass
            region.ledger_row = None

    def list_regions(self):
        with self._lock:
            return [
                (r.region_id, r.device_id, r.byte_size)
                for r in self._regions.values()
            ]

    def _get(self, region_id: str) -> _Region:
        region = self._regions.get(region_id)
        if region is None:
            raise InferenceServerException(
                "unknown TPU arena region", status="NOT_FOUND"
            )
        return region

    # -- cross-host pull path (docs/cross_host_arena.md) -----------------

    def resolve_pull_handle(self, raw_handle: bytes) -> _Region:
        """Authenticate a handle for PullRegion: the full descriptor
        (arena_id + region + nonce) must match — a consumer can only
        pull what the owner's handle authorizes. NOT_FOUND (vs the
        registration path's INVALID_ARGUMENT) so the consumer can tell
        a dead handle from a malformed one."""
        return self._authenticate(raw_handle, "NOT_FOUND")

    def snapshot_segments(self, region_id: str):
        """Consistent segment-list snapshot for the pull stream.
        Segment arrays are immutable (writes replace the list, never
        mutate an array), so serializing each segment AFTER releasing
        the lock streams a coherent point-in-time view without holding
        the region lock across device->host transfers."""
        region = self._get(region_id)
        with region.lock:
            return list(region.segments)

    def adopt_segment(self, region_id: str, offset: int, nbytes: int,
                      datatype: Optional[str], shape, array) -> None:
        """Insert an externally-assembled segment (the consumer end of
        a pull): ``array`` is already typed and placed on this host —
        metadata comes from the owner's stream, bounds are re-checked
        here."""
        region = self._get(region_id)
        if offset < 0 or offset + nbytes > region.byte_size:
            raise InferenceServerException(
                "pulled segment [%d, %d) exceeds region size %d"
                % (offset, offset + nbytes, region.byte_size),
                status="INVALID_ARGUMENT")
        segment = _Segment(offset, nbytes, datatype or None,
                           list(shape) if shape is not None else None, array)
        with region.lock:
            self._insert_segment(region, segment)

    # -- data plane ------------------------------------------------------

    def write(self, region_id: str, offset: int, data: bytes,
              datatype: str = "", shape=None) -> None:
        """Host bytes -> device segment (the one host->device hop).
        With dtype/shape metadata the segment stores a typed array at
        any offset, so multi-tensor layouts keep per-tensor dtype."""
        jax = self._jax
        region = self._get(region_id)
        if offset + len(data) > region.byte_size:
            raise InferenceServerException(
                "write of %d bytes at offset %d exceeds region size %d"
                % (len(data), offset, region.byte_size),
                status="INVALID_ARGUMENT",
            )
        if datatype and shape is not None:
            if datatype == "BYTES":
                # variable-length elements stay host-side
                array = deserialize_bytes_tensor(data).reshape(shape)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                host = np.frombuffer(data, dtype=np_dtype).reshape(shape)
                array = jax.device_put(host, region.device)
            segment = _Segment(offset, len(data), datatype, list(shape),
                               array)
        else:
            array = jax.device_put(
                np.frombuffer(data, np.uint8), region.device)
            segment = _Segment(offset, len(data), None, None, array)
        with region.lock:
            self._insert_segment(region, segment)

    def _insert_segment(self, region: _Region, segment: _Segment) -> None:
        """Place a segment, carving out overlaps. Only the overlapped
        segments are touched (device->host per slice); untouched
        tensors keep their device arrays — never a whole-region
        round-trip. Caller holds region.lock."""
        jax = self._jax
        kept = []
        for existing in region.segments:
            if existing.end <= segment.offset or \
                    existing.offset >= segment.end:
                kept.append(existing)
                continue
            if (existing.offset >= segment.offset
                    and existing.end <= segment.end):
                continue  # fully covered: dropped
            if existing.datatype == "BYTES":
                # A partially-overwritten serialized BYTES tensor has
                # no meaningful byte remainder (the length-prefixed
                # framing is invalidated) — drop it so reads never see
                # stale framing bytes past a smaller replacement.
                continue
            # Partial overlap: keep the non-overlapped remainder(s) as
            # raw byte runs (host hop for this segment only; the view
            # is sliced without a second whole-buffer copy).
            raw = self._segment_view(existing)
            if existing.offset < segment.offset:
                head = raw[: segment.offset - existing.offset]
                kept.append(_Segment(
                    existing.offset, len(head), None, None,
                    jax.device_put(np.frombuffer(head, np.uint8),
                                   region.device)))
            if existing.end > segment.end:
                tail = raw[segment.end - existing.offset:]
                kept.append(_Segment(
                    segment.end, len(tail), None, None,
                    jax.device_put(np.frombuffer(tail, np.uint8),
                                   region.device)))
        kept.append(segment)
        kept.sort(key=lambda s: s.offset)
        region.segments = kept

    @staticmethod
    def _segment_view(segment: _Segment) -> memoryview:
        """ONE host materialization of a segment, served as a
        read-only byte view (client_tpu.server.fetch.host_view). The
        old ``np.asarray(...).tobytes()`` materialized the array and
        then copied the whole buffer AGAIN into a bytes object; every
        internal consumer (read windows, carve remainders, pull-stream
        chunking) slices this view instead."""
        from client_tpu.server.fetch import host_view, start_async_copy

        if segment.datatype == "BYTES":
            from client_tpu.utils import serialize_byte_tensor

            return host_view(serialize_byte_tensor(
                np.asarray(segment.array)))
        start_async_copy(segment.array)
        return host_view(segment.array)

    @classmethod
    def _segment_bytes(cls, segment: _Segment) -> bytes:
        """Owned-bytes form of :meth:`_segment_view` for consumers
        that must outlive the backing array (kept for compatibility;
        prefer the view)."""
        return bytes(cls._segment_view(segment))

    def as_typed_array(self, region_id: str, offset: int, byte_size: int,
                       datatype: str, shape):
        """Resolve a slice as a device array of datatype/shape for
        model consumption. Fast path: a segment already holds exactly
        that typed array at that offset — hand it over untouched."""
        jax = self._jax
        region = self._get(region_id)
        with region.lock:
            if not region.segments:
                raise InferenceServerException(
                    "TPU region read before any write",
                    status="INVALID_ARGUMENT",
                )
            for segment in region.segments:
                if (segment.offset == offset
                        and segment.datatype == datatype
                        and segment.shape == list(shape)):
                    return segment.array
            if datatype == "BYTES":
                for segment in region.segments:
                    if (segment.offset == offset
                            and segment.datatype == "BYTES"):
                        return segment.array.reshape(shape)
                raise InferenceServerException(
                    "region does not hold a BYTES tensor at offset %d"
                    % offset,
                    status="INVALID_ARGUMENT",
                )
            elem = wire_dtype_element_size(datatype)
            count = elem * int(np.prod(shape)) if len(shape) else elem
            if offset + count > region.byte_size:
                raise InferenceServerException(
                    "typed view exceeds region bounds",
                    status="INVALID_ARGUMENT",
                )
            cover = [s for s in region.segments
                     if s.offset < offset + count and s.end > offset]
            if any(s.datatype == "BYTES" for s in cover):
                # Serialized BYTES framing is not byte-addressable
                # numeric data — reinterpreting it would hand the
                # model garbage.
                raise InferenceServerException(
                    "cannot view BYTES region as %s" % datatype,
                    status="INVALID_ARGUMENT",
                )
            # Single covering non-BYTES segment: reinterpret on device
            # (dynamic_slice + bitcast), no host hop.
            if (len(cover) == 1 and cover[0].datatype != "BYTES"
                    and cover[0].offset <= offset
                    and cover[0].end >= offset + count):
                import jax.numpy as jnp

                segment = cover[0]
                flat = segment.array.reshape(-1)
                if flat.dtype == jnp.bool_:  # bitcast rejects bool
                    flat = flat.astype(jnp.uint8)
                if flat.dtype != jnp.uint8:
                    flat = jax.lax.bitcast_convert_type(
                        flat, jnp.uint8).reshape(-1)
                np_dtype = triton_to_np_dtype(datatype)
                window = jax.lax.dynamic_slice(
                    flat, (offset - segment.offset,), (count,))
                if datatype == "BOOL":  # u8 0/1 -> bool
                    typed = window.astype(jnp.bool_)
                else:
                    typed = jax.lax.bitcast_convert_type(
                        window.reshape(-1, elem), jnp.dtype(np_dtype))
                return typed.reshape(shape)
            # Slice spans several segments (or gaps): assemble the
            # covered bytes on host — touching only those segments —
            # and upload the window once.
            data = self._read_locked(region, offset, count)
        # Upload OUTSIDE the region lock: a host->device transfer can
        # stall behind the device queue, and holding the lock across
        # it would block every concurrent reader/writer of this region
        # for the duration (tpulint: lock-discipline). The bytes are
        # already copied out, so a concurrent write can't tear them.
        host = np.frombuffer(
            data, dtype=triton_to_np_dtype(datatype)).reshape(shape)
        return jax.device_put(host, region.device)

    def store(self, region_id: str, offset: int, byte_size: int, value) -> int:
        """Place an inference output into the region by reference (the
        zero-copy 'write' — a segment swap at any offset). Returns the
        logical byte size stored."""
        jax = self._jax
        region = self._get(region_id)
        if isinstance(value, np.ndarray) and value.dtype.kind in ("O", "S", "U"):
            from client_tpu.utils import serialize_byte_tensor

            nbytes = int(serialize_byte_tensor(value).size)
            datatype = "BYTES"
            stored = value
        else:
            if not hasattr(value, "dtype"):
                value = np.asarray(value)
            nbytes = int(np.prod(value.shape)) * value.dtype.itemsize
            from client_tpu.utils import np_to_wire_dtype

            datatype = np_to_wire_dtype(value.dtype)
            stored = value
            if isinstance(value, np.ndarray):
                stored = jax.device_put(value, region.device)
        if nbytes > byte_size or offset + nbytes > region.byte_size:
            raise InferenceServerException(
                "output of %d bytes exceeds TPU region slice (%d)"
                % (nbytes, min(byte_size, region.byte_size - offset)),
                status="INVALID_ARGUMENT",
            )
        with region.lock:
            self._insert_segment(region, _Segment(
                offset, nbytes, datatype, list(stored.shape), stored))
        return nbytes

    def read(self, region_id: str, offset: int, byte_size: int):
        """Device region -> host bytes (inspection path). Serializes
        only the segments overlapping the window. When ONE segment
        covers the whole window — the head-segment and whole-region
        common cases — the returned value is a memoryview over the
        single host materialization (no assembly copy, no tobytes
        copy); multi-segment windows assemble into bytes as before.
        Serialization runs OUTSIDE the region lock: segment arrays are
        immutable (writes replace the list), so a snapshot of the list
        is a coherent point-in-time view and the device->host transfer
        never blocks concurrent readers/writers."""
        region = self._get(region_id)
        with region.lock:
            if not region.segments:
                return b"\x00" * (byte_size or region.byte_size)
            if byte_size == 0:  # "to end" = the stored payload
                end = max(s.end for s in region.segments)
                byte_size = max(end - offset, 0)
                if byte_size == 0:
                    return b""
            segments = list(region.segments)
        for segment in segments:
            if segment.offset <= offset and \
                    segment.end >= offset + byte_size:
                view = self._segment_view(segment)
                lo = offset - segment.offset
                return view[lo:lo + byte_size]
        return self._assemble(segments, offset, byte_size)

    def _read_locked(self, region: _Region, offset: int,
                     byte_size: int) -> bytes:
        """Assemble [offset, offset+byte_size) from overlapping
        segments, zero-filling gaps. Caller holds region.lock."""
        return self._assemble(region.segments, offset, byte_size)

    def _assemble(self, segments, offset: int, byte_size: int) -> bytes:
        """Multi-segment window assembly over an immutable segment
        snapshot; each segment contributes a slice of its single host
        view (no per-segment tobytes copy)."""
        window = bytearray(byte_size)
        for segment in segments:
            if segment.end <= offset or segment.offset >= offset + byte_size:
                continue
            raw = self._segment_view(segment)
            src_lo = max(0, offset - segment.offset)
            src_hi = min(len(raw), offset + byte_size - segment.offset)
            dst_lo = segment.offset + src_lo - offset
            window[dst_lo:dst_lo + (src_hi - src_lo)] = raw[src_lo:src_hi]
        return bytes(window)
