"""Server-owned TPU HBM arena: the TPU-native shared-memory data plane.

Re-designs the reference's CUDA shared-memory model (cudaMalloc +
cudaIpcGetMemHandle + cudaIpcOpenMemHandle, utils/cuda_shared_memory/
__init__.py:107-149) for TPU reality: one process owns the device, so
"shared" regions are *named slots* in the owning process. A slot holds
a ``jax.Array``; the handle handed to clients is a signed logical
descriptor, not a pointer.

Zero-copy properties:
- input resolution hands the slot's device array to the jitted model
  unchanged (no host round-trip, no copy);
- output placement stores the result array by reference — on TPU an
  "in-place write to shared memory" is a reference swap;
- host data written by a remote client crosses host->device once at
  population time, never on the request path (matching how
  perf-harness shm mode populates regions once and reuses them).
"""

from __future__ import annotations

import json
import secrets
import threading
import uuid
from typing import Dict, Optional

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
    wire_dtype_element_size,
)


class _Region:
    def __init__(self, region_id: str, device, device_id: int, byte_size: int,
                 nonce: str):
        self.region_id = region_id
        self.device = device
        self.device_id = device_id
        self.byte_size = byte_size
        self.nonce = nonce
        self.lock = threading.Lock()
        # Either a typed device array covering the whole region
        # payload, or a flat uint8 device array of byte_size bytes.
        self.array = None
        self.datatype: Optional[str] = None
        self.shape: Optional[list] = None


class TpuArena:
    """Named HBM slots on the arena's devices."""

    def __init__(self, platform: Optional[str] = None):
        import jax

        self._jax = jax
        if platform:
            self._devices = jax.devices(platform)
        else:
            self._devices = jax.devices()
        self.arena_id = uuid.uuid4().hex[:12]
        self._regions: Dict[str, _Region] = {}
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def device_for(self, device_id: int):
        if device_id < 0 or device_id >= len(self._devices):
            raise InferenceServerException(
                "device_id %d out of range (%d devices)"
                % (device_id, len(self._devices)),
                status="INVALID_ARGUMENT",
            )
        return self._devices[device_id]

    def create_region(self, byte_size: int, device_id: int = 0) -> bytes:
        """Allocate a slot; returns the serialized raw handle."""
        if byte_size <= 0:
            raise InferenceServerException(
                "byte_size must be positive", status="INVALID_ARGUMENT"
            )
        device = self.device_for(device_id)
        region_id = uuid.uuid4().hex
        nonce = secrets.token_hex(8)
        region = _Region(region_id, device, device_id, byte_size, nonce)
        with self._lock:
            self._regions[region_id] = region
        return self._serialize_handle(region)

    def _serialize_handle(self, region: _Region) -> bytes:
        return json.dumps({
            "arena_id": self.arena_id,
            "region_id": region.region_id,
            "device_id": region.device_id,
            "byte_size": region.byte_size,
            "nonce": region.nonce,
        }).encode()

    def validate_handle(self, raw_handle: bytes, device_id: int,
                        byte_size: int) -> str:
        """Check a client-provided handle against this arena; returns
        the region_id (used by TpuSharedMemoryRegister)."""
        try:
            descriptor = json.loads(raw_handle)
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise InferenceServerException(
                "malformed TPU shared memory handle", status="INVALID_ARGUMENT"
            )
        region = self._regions.get(descriptor.get("region_id", ""))
        if (
            region is None
            or descriptor.get("arena_id") != self.arena_id
            or descriptor.get("nonce") != region.nonce
        ):
            raise InferenceServerException(
                "TPU shared memory handle does not match any arena region",
                status="INVALID_ARGUMENT",
            )
        if byte_size > region.byte_size:
            raise InferenceServerException(
                "registered byte_size %d exceeds region size %d"
                % (byte_size, region.byte_size),
                status="INVALID_ARGUMENT",
            )
        if device_id != region.device_id:
            raise InferenceServerException(
                "registered device_id %d does not match region device %d"
                % (device_id, region.device_id),
                status="INVALID_ARGUMENT",
            )
        return region.region_id

    def destroy_region(self, region_id: str) -> None:
        with self._lock:
            region = self._regions.pop(region_id, None)
        if region is not None:
            region.array = None  # drop the HBM buffer reference

    def list_regions(self):
        with self._lock:
            return [
                (r.region_id, r.device_id, r.byte_size)
                for r in self._regions.values()
            ]

    def _get(self, region_id: str) -> _Region:
        region = self._regions.get(region_id)
        if region is None:
            raise InferenceServerException(
                "unknown TPU arena region", status="NOT_FOUND"
            )
        return region

    # -- data plane ------------------------------------------------------

    def write(self, region_id: str, offset: int, data: bytes,
              datatype: str = "", shape=None) -> None:
        """Host bytes -> device slot (the one host->device hop). With
        dtype/shape metadata the slot stores a typed array directly."""
        jax = self._jax
        region = self._get(region_id)
        if offset + len(data) > region.byte_size:
            raise InferenceServerException(
                "write of %d bytes at offset %d exceeds region size %d"
                % (len(data), offset, region.byte_size),
                status="INVALID_ARGUMENT",
            )
        with region.lock:
            if datatype and shape is not None and offset == 0:
                if datatype == "BYTES":
                    # variable-length elements stay host-side
                    arr = deserialize_bytes_tensor(data).reshape(shape)
                    region.array = arr
                else:
                    np_dtype = triton_to_np_dtype(datatype)
                    host = np.frombuffer(data, dtype=np_dtype).reshape(shape)
                    region.array = jax.device_put(host, region.device)
                region.datatype = datatype
                region.shape = list(shape)
                return
            # raw byte write: merge into the flat uint8 image
            flat = self._as_flat_u8(region)
            host = np.asarray(flat)  # device->host (rare path)
            host = host.copy()
            host[offset : offset + len(data)] = np.frombuffer(data, np.uint8)
            region.array = jax.device_put(host, region.device)
            region.datatype = None
            region.shape = None

    def _as_flat_u8(self, region: _Region):
        jax = self._jax
        if region.array is None:
            return jax.device_put(
                np.zeros(region.byte_size, dtype=np.uint8), region.device
            )
        if region.datatype is None:
            return region.array
        if isinstance(region.array, np.ndarray):  # BYTES host-side
            raise InferenceServerException(
                "cannot view BYTES region as raw bytes", status="INVALID_ARGUMENT"
            )
        # typed -> raw view without leaving the device
        import jax.numpy as jnp

        flat = region.array.reshape(-1)
        if flat.dtype == jnp.bool_:  # bitcast rejects bool
            flat = flat.astype(jnp.uint8)
        u8 = jax.lax.bitcast_convert_type(flat, jnp.uint8).reshape(-1)
        pad = region.byte_size - u8.size
        if pad > 0:
            u8 = jnp.concatenate([u8, jnp.zeros(pad, dtype=jnp.uint8)])
        return u8

    def as_typed_array(self, region_id: str, offset: int, byte_size: int,
                       datatype: str, shape):
        """Resolve the slot as a device array of datatype/shape for
        model consumption. Fast path: the slot already holds exactly
        that typed array — hand it over untouched."""
        jax = self._jax
        region = self._get(region_id)
        with region.lock:
            if (
                offset == 0
                and region.datatype == datatype
                and region.shape == list(shape)
                and region.array is not None
            ):
                return region.array
            if region.array is None:
                raise InferenceServerException(
                    "TPU region read before any write", status="INVALID_ARGUMENT"
                )
            if datatype == "BYTES":
                if isinstance(region.array, np.ndarray):
                    return region.array.reshape(shape)
                raise InferenceServerException(
                    "region does not hold a BYTES tensor",
                    status="INVALID_ARGUMENT",
                )
            flat = self._as_flat_u8(region)
            import jax.numpy as jnp

            elem = wire_dtype_element_size(datatype)
            count = elem * int(np.prod(shape)) if len(shape) else elem
            if offset + count > region.byte_size:
                raise InferenceServerException(
                    "typed view exceeds region bounds", status="INVALID_ARGUMENT"
                )
            np_dtype = triton_to_np_dtype(datatype)
            window = jax.lax.dynamic_slice(flat, (offset,), (count,))
            if datatype == "BOOL":  # bitcast rejects bool: u8 0/1 -> bool
                typed = window.astype(jnp.bool_)
            else:
                typed = jax.lax.bitcast_convert_type(
                    window.reshape(-1, elem), jnp.dtype(np_dtype)
                )
            return typed.reshape(shape)

    def store(self, region_id: str, offset: int, byte_size: int, value) -> int:
        """Place an inference output into the slot by reference (the
        zero-copy 'write'). Returns the logical byte size stored."""
        jax = self._jax
        region = self._get(region_id)
        if isinstance(value, np.ndarray) and value.dtype.kind in ("O", "S", "U"):
            from client_tpu.utils import serialize_byte_tensor

            nbytes = int(serialize_byte_tensor(value).size)
            datatype = "BYTES"
            stored = value
        else:
            if not hasattr(value, "dtype"):
                value = np.asarray(value)
            nbytes = int(np.prod(value.shape)) * value.dtype.itemsize
            from client_tpu.utils import np_to_wire_dtype

            datatype = np_to_wire_dtype(value.dtype)
            stored = value
            if isinstance(value, np.ndarray):
                stored = jax.device_put(value, region.device)
        if nbytes > byte_size or offset + nbytes > region.byte_size:
            raise InferenceServerException(
                "output of %d bytes exceeds TPU region slice (%d)"
                % (nbytes, min(byte_size, region.byte_size - offset)),
                status="INVALID_ARGUMENT",
            )
        if offset:
            # non-zero offset: merge into the raw byte image (host hop;
            # the zero-copy contract applies to whole-slot placement)
            if datatype == "BYTES":
                from client_tpu.utils import serialize_byte_tensor as _sbt

                data = _sbt(np.asarray(stored)).tobytes()
            else:
                data = np.asarray(stored).tobytes()
            self.write(region.region_id, offset, data)
            return nbytes
        with region.lock:
            region.array = stored
            region.datatype = datatype
            region.shape = list(stored.shape)
        return nbytes

    def read(self, region_id: str, offset: int, byte_size: int) -> bytes:
        """Device slot -> host bytes (inspection path)."""
        region = self._get(region_id)
        with region.lock:
            if region.array is None:
                return b"\x00" * (byte_size or region.byte_size)
            if region.datatype == "BYTES":
                from client_tpu.utils import serialize_byte_tensor

                data = serialize_byte_tensor(region.array).tobytes()
            elif region.datatype is not None:
                data = np.asarray(region.array).tobytes()
            else:
                data = np.asarray(region.array).tobytes()
        if byte_size == 0:  # "to end" = the stored payload (BYTES reads)
            return data[offset:]
        if offset >= len(data):
            return b"\x00" * byte_size
        chunk = data[offset : offset + byte_size]
        if len(chunk) < byte_size:  # zero-fill past the stored payload
            chunk = chunk + b"\x00" * (byte_size - len(chunk))
        return chunk
