"""Transport-neutral inference server core.

Executes KServe-v2 requests against a ModelRepository. Both the gRPC
servicer and the HTTP app convert their wire forms to the protos in
client_tpu.protocol and call into this core; the perf harness's
in-process backend (the analogue of the reference's triton_c_api
backend, /root/reference/src/c++/perf_analyzer/client_backend/
triton_c_api/) calls it directly with no serialization at all.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

import numpy as np

from client_tpu import status_map
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server import autoscale
from client_tpu.server import cache as cache_mod
from client_tpu.server import cancel as cancel_mod
from client_tpu.server import chaos
from client_tpu.server import devstats as devstats_mod
from client_tpu.server import fetch as relay
from client_tpu.server import flight as flightrec
from client_tpu.server import hbm as hbm_mod
from client_tpu.server import slo as sloengine
from client_tpu.server import telemetry as telemetry_mod
from client_tpu.server import tracing as spantrace
from client_tpu.server.cache import (
    DEFAULT_CACHE_BYTES,
    ResponseCache,
    request_cache_key,
    wants_response_cache,
)
from client_tpu.server.memory import SharedMemoryManager
from client_tpu.server.model import ServedModel
from client_tpu.server.repository import ModelRepository
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    np_to_wire_dtype,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

_LOG = logging.getLogger("client_tpu.server")

SERVER_NAME = "client_tpu_server"
SERVER_VERSION = "0.1.0"
SERVER_EXTENSIONS = [
    "classification",
    "sequence",
    "model_repository",
    "schedule_policy",
    "model_configuration",
    "system_shared_memory",
    "tpu_shared_memory",
    "binary_tensor_data",
    "statistics",
    "trace",
    "logging",
]


class _ModelStats:
    """Cumulative per-model counters backing ModelStatistics."""

    def __init__(self):
        self.lock = threading.Lock()
        self.inference_count = 0
        self.execution_count = 0
        self.success_count = 0
        self.success_ns = 0
        self.fail_count = 0
        self.fail_ns = 0
        self.queue_ns = 0
        self.compute_input_ns = 0
        self.compute_infer_ns = 0
        self.compute_output_ns = 0
        self.last_inference_ms = 0
        # Queue-policy drops: admission rejections (queue full) and
        # queue-deadline expiries — every dropped request is counted
        # somewhere (ModelStatistics.reject_count/timeout_count and
        # the tpu_request_*_total Prometheus families).
        self.rejected_count = 0
        self.timeout_count = 0
        # Overload sheds (lowest-priority-first drops: displacement at
        # a full queue, watermark sheds) — distinct from plain rejects
        # so dashboards can tell "queue full" from "QoS made room".
        self.shed_count = 0
        # Per-priority-class rows (ModelStatistics.priority_stats):
        # level -> [success, reject, timeout, shed, queue_ns].
        self.priority_hist: Dict[int, list] = {}
        # Per-tenant rows (ModelStatistics.tenant_stats):
        # tenant -> [success, reject, fail, duration_ns]. Quota
        # rejects land in `reject`; queue-policy drops are priority
        # rows' business.
        self.tenant_hist: Dict[str, list] = {}
        # Fused-batch-size histogram fed by the dynamic batcher's
        # stats hook: executed batch size -> [executions, compute_ns,
        # fetch_ns] (renders as ModelStatistics.batch_stats).
        self.batch_hist: Dict[int, list] = {}
        # Response-cache path counters (ModelStatistics.cache_*): hits
        # — direct lookups AND single-flight followers — never execute
        # the model, so they count toward inference_count but not
        # execution_count, and contribute NOTHING to the queue/compute
        # sections (the perf-harness caveat).
        self.cache_hit_count = 0
        self.cache_hit_ns = 0
        self.cache_miss_count = 0
        self.cache_miss_ns = 0
        # Streaming-token telemetry (ModelStatistics.stream_stats):
        # server-observed TTFT / inter-response gaps plus response and
        # completed-stream counts. The telemetry histograms carry the
        # distributions; these counters carry the means over the
        # statistics protocol both transports already speak.
        self.stream_count = 0
        self.stream_response_count = 0
        self.stream_first_count = 0
        self.stream_first_ns = 0
        self.stream_inter_count = 0
        self.stream_inter_ns = 0
        # Cancellation accounting: stage boundary the signal landed at
        # -> count (tpu_request_cancelled_total{model,stage}), plus
        # device compute spent on requests that were already cancelled
        # when their execution completed (tpu_wasted_compute_us — the
        # Tail-at-Scale wasted-work amplification number cancellation
        # exists to shrink).
        self.cancelled_hist: Dict[str, int] = {}
        self.wasted_compute_ns = 0

    def _priority_row(self, level: int) -> list:
        """[success, reject, timeout, shed, queue_ns] for one class
        (caller holds the lock)."""
        return self.priority_hist.setdefault(level, [0, 0, 0, 0, 0])

    def record(self, batch: int, queue_ns: int, ci_ns: int, infer_ns: int,
               co_ns: int, ok: bool, executions: int = 1,
               total_ns: Optional[int] = None, priority: int = 0):
        # total_ns overrides the component sum for paths whose time
        # must not land in any queue/compute bucket (cache hits).
        total = queue_ns + ci_ns + infer_ns + co_ns \
            if total_ns is None else total_ns
        with self.lock:
            if ok:
                self.inference_count += batch
                self.execution_count += executions
                self.success_count += 1
                self.success_ns += total
                self.queue_ns += queue_ns
                self.compute_input_ns += ci_ns
                self.compute_infer_ns += infer_ns
                self.compute_output_ns += co_ns
                if priority:
                    row = self._priority_row(priority)
                    row[0] += 1
                    row[4] += queue_ns
            else:
                self.fail_count += 1
                self.fail_ns += total
            self.last_inference_ms = int(time.time() * 1000)

    def record_rejected(self, priority: int = 0):
        """Queue-policy admission rejection (max_queue_size hit)."""
        with self.lock:
            self.rejected_count += 1
            if priority:
                self._priority_row(priority)[1] += 1

    def record_timeout(self, priority: int = 0):
        """Queue-deadline expiry (request dropped before dispatch)."""
        with self.lock:
            self.timeout_count += 1
            if priority:
                self._priority_row(priority)[2] += 1

    def record_shed(self, priority: int = 0):
        """Overload shed: the request was dropped to protect a higher
        class (displacement / watermark), lowest-priority-first."""
        with self.lock:
            self.shed_count += 1
            if priority:
                self._priority_row(priority)[3] += 1

    def record_cancelled(self, stage: str):
        """One request abandoned at `stage` (client disconnect, wire
        cancel, hedge loser, or post-dispatch deadline expiry)."""
        with self.lock:
            self.cancelled_hist[stage] = \
                self.cancelled_hist.get(stage, 0) + 1

    def record_wasted_ns(self, ns: int):
        """Device compute that completed for a caller already gone."""
        if ns <= 0:
            return
        with self.lock:
            self.wasted_compute_ns += int(ns)

    def _tenant_row(self, tenant: str) -> list:
        """[success, reject, fail, duration_ns] for one tenant (caller
        holds the lock). Cardinality-bounded like the quota manager:
        identity is client-supplied, so past the cap new names fold
        into one overflow row instead of growing without bound."""
        row = self.tenant_hist.get(tenant)
        if row is None:
            from client_tpu.server.qos import (
                MAX_TRACKED_TENANTS,
                OVERFLOW_TENANT,
            )

            if len(self.tenant_hist) >= MAX_TRACKED_TENANTS:
                tenant = OVERFLOW_TENANT
            row = self.tenant_hist.setdefault(tenant, [0, 0, 0, 0])
        return row

    def record_tenant(self, tenant: str, ok: bool, ns: int):
        """End-to-end per-tenant accounting for one served request."""
        with self.lock:
            row = self._tenant_row(tenant)
            if ok:
                row[0] += 1
                row[3] += max(int(ns), 0)
            else:
                row[2] += 1

    def record_tenant_rejected(self, tenant: str):
        """Quota reject (token bucket / concurrency cap) at the door."""
        with self.lock:
            self._tenant_row(tenant)[1] += 1

    def record_cache_hit(self, ns: int):
        """One request served from the response cache (or coalesced
        onto an identical in-flight execution). ``ns`` is the
        end-to-end hit-path duration."""
        with self.lock:
            self.cache_hit_count += 1
            self.cache_hit_ns += ns

    def record_cache_miss(self, ns: int):
        """One cache-eligible request that had to execute. ``ns`` is
        the end-to-end miss-path duration (lookup + execute +
        insert)."""
        with self.lock:
            self.cache_miss_count += 1
            self.cache_miss_ns += ns

    def record_stream_first(self, ns: int):
        """Server-observed time from stream admission to the first
        response the model produced (TTFT for token streams)."""
        with self.lock:
            self.stream_first_count += 1
            self.stream_first_ns += max(int(ns), 0)
            self.stream_response_count += 1

    def record_stream_gap(self, ns: int):
        """Server-observed gap between consecutive streamed responses
        (inter-token latency for one-token-per-response streams)."""
        with self.lock:
            self.stream_inter_count += 1
            self.stream_inter_ns += max(int(ns), 0)
            self.stream_response_count += 1

    def record_stream_done(self):
        """One stream (decoupled or unary-through-stream) completed."""
        with self.lock:
            self.stream_count += 1

    def record_batch(self, size: int, compute_ns: int, fetch_ns: int):
        """Dynamic-batcher stats hook: one fused execution at `size`."""
        if size <= 0:
            return
        with self.lock:
            entry = self.batch_hist.setdefault(size, [0, 0, 0])
            entry[0] += 1
            entry[1] += compute_ns
            entry[2] += fetch_ns


def mint_request_id(request: pb.ModelInferRequest) -> None:
    """Request-id correlation: a transport front-end stamps an id on
    requests that carry none, so responses, trace records, and error
    logs can always be joined to a client-side result. Only call this
    on a per-call proto the transport owns — direct core callers may
    share one request object across threads."""
    if not request.id:
        request.id = uuid.uuid4().hex[:16]


def stream_error_response(request, message):
    """Decoupled errors ride the stream (never abort it) and carry the
    request id so a client pipelining many requests on one stream can
    attribute the failure (concurrent dispatch means arrival order
    proves nothing)."""
    response = pb.ModelStreamInferResponse(error_message=message)
    response.infer_response.id = request.id
    return response


class _TenantAdmission:
    """Pairs tenant-quota admission with release + accounting so the
    unary and streaming paths cannot drift. ``__enter__`` resolves the
    request's tenant and spends a quota token/in-flight slot (a reject
    records per-tenant accounting and raises RESOURCE_EXHAUSTED);
    ``__exit__`` returns the slot and records latency on EVERY exit —
    including failures between admission and model acquire, which
    would otherwise leak the slot and starve a concurrency-capped
    tenant. Callers set ``ok = True`` on success and ``model_name``
    once a validated model is known (per-model tenant rows must not be
    minted for bogus model names)."""

    __slots__ = ("_core", "_request", "_trace_context", "tenant", "ok",
                 "model_name", "_held", "_t0")

    def __init__(self, core: "InferenceServerCore",
                 request: pb.ModelInferRequest,
                 trace_context: Optional[str] = None):
        self._core = core
        self._request = request
        # Threaded through so a quota-rejected request's flight record
        # adopts the caller's W3C trace id (joinable by distributed
        # trace, like every other kept record).
        self._trace_context = trace_context
        self.tenant = None
        self.ok = False
        self.model_name: Optional[str] = None
        self._held = False
        self._t0 = 0

    def __enter__(self) -> "_TenantAdmission":
        core, request = self._core, self._request
        tenant = core._tenant_of(request)
        quotas = core.tenant_quotas
        if tenant is not None and quotas is not None and quotas.enabled:
            try:
                # acquire may resolve the identity to the shared
                # overflow bucket (cardinality bound) — release and
                # accounting must use the resolved name.
                tenant = quotas.acquire(tenant)
                self._held = True
            except InferenceServerException as e:
                # Per-model reject accounting only for KNOWN stats
                # entries: a quota-rejected request naming a bogus
                # model must not mint permanent per-model series.
                with core._stats_lock:
                    stats = core._stats.get(request.model_name)
                if stats is not None:
                    stats.record_tenant_rejected(tenant)
                # Quota rejects fire before any scratch capture —
                # retain them in the flight ring too (reason "quota",
                # joined to the caller's trace context).
                core._flight_admission_reject(request,
                                              self._trace_context, e)
                _LOG.debug("request %s for tenant '%s' rejected: %s",
                           request.id, tenant, e)
                raise
        self.tenant = tenant
        self._t0 = time.monotonic_ns() if tenant is not None else 0
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.tenant is not None:
            duration_ns = time.monotonic_ns() - self._t0
            if self._held:
                self._core.tenant_quotas.release(
                    self.tenant, self.ok, duration_ns)
            if self.model_name is not None:
                self._core._stats_for(self.model_name).record_tenant(
                    self.tenant, self.ok, duration_ns)
            if self.ok:
                # The per-tenant duration HISTOGRAM (the sum-only
                # counter this family used to be had no paired count,
                # so rate() yielded nothing interpretable).
                self._core.telemetry.observe_tenant(
                    self.tenant, duration_ns / 1000.0)
        return False


def _escape_label_value(value) -> str:
    """Prometheus exposition-format label-value escaping. Tenant is the
    one CLIENT-supplied label value on /metrics; a quote, backslash, or
    newline inside it must not corrupt the whole exposition page."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _param_value(param: pb.InferParameter):
    which = param.WhichOneof("parameter_choice")
    return getattr(param, which) if which else None


class InferenceServerCore:
    def __init__(self, repository: ModelRepository, tpu_arena=None,
                 cache_size: Optional[int] = None,
                 tenant_quotas=None):
        self.repository = repository
        self.memory = SharedMemoryManager(tpu_arena)
        # Per-tenant admission control (client_tpu.server.qos
        # TenantQuotaManager; None/disabled = zero per-request cost).
        # Enforced at the very front of infer(), before the model is
        # even acquired: a tenant over its token bucket or concurrency
        # cap is rejected RESOURCE_EXHAUSTED (HTTP 429) with a
        # Retry-After derived from the bucket refill time.
        self.tenant_quotas = tenant_quotas
        # Content-addressed response cache (server-level byte budget;
        # models opt in via response_cache.enable). 0 disables. The
        # repository's unload drain path invalidates a model's entries
        # on reload/unload — a new instance may produce different
        # bytes for the same inputs.
        self.response_cache = ResponseCache(
            DEFAULT_CACHE_BYTES if cache_size is None else cache_size)
        repository.add_unload_listener(self.response_cache.invalidate_model)
        # Always-on latency histograms + streaming-token telemetry
        # (client_tpu.server.telemetry): scrape-cheap SLO distributions
        # for every request at every serving stage, exposed on /metrics
        # as Prometheus histogram families. CLIENT_TPU_TELEMETRY=off
        # disables recording (the bench's A/B arm).
        self.telemetry = telemetry_mod.ServerTelemetry()
        # Flight recorder (client_tpu.server.flight): every request's
        # span tree is captured into a scratch trace regardless of
        # trace_rate; a RETROACTIVE keep decision at completion
        # retains errors, sheds, timeouts, quota rejects, and
        # slower-than-threshold requests in bounded per-model rings —
        # dumpable over GET /v2/debug/flight. CLIENT_TPU_FLIGHT=off
        # disables capture (the flight_overhead bench A/B arm).
        self.flight = flightrec.FlightRecorder(telemetry=self.telemetry)
        # SLO engine (client_tpu.server.slo): error-budget burn rate
        # over fast/slow windows for every model declaring an `slo`
        # block, computed from the telemetry histograms + the success
        # counters above and exposed as the tpu_slo_* families plus
        # SloStatistics. Burns that flip a model unhealthy stamp the
        # flight-ring traces that contributed to them.
        self.slo = sloengine.SloEngine(
            targets_fn=self._slo_targets,
            collect_fn=self._slo_collect,
            incident_hook=self.flight.mark_incident,
        )
        # Device-axis observability (client_tpu.server.devstats):
        # process-wide — every in-process core shares the same chips,
        # so they share one HBM ledger, busy-time counters, compile
        # tracker, and profiler. Recompile storms stamp THIS core's
        # flight ring like SLO burns and breaker trips do.
        self.devstats = devstats_mod.get()
        self.devstats.add_incident_hook(self.flight.mark_incident)
        # HBM allocator (client_tpu.server.hbm): process-wide like
        # devstats — the single owner of device memory for weights,
        # KV slabs, arena regions, and ensemble-interior hand-offs.
        # Cold pageable models' weights move to host under pressure
        # or at scale-to-zero and restore chunked-parallel on the
        # next arrival; admissions arbitrate per-device.
        self.hbm = hbm_mod.get()
        # Autoscale controller (client_tpu.server.autoscale): the
        # feedback loop that resizes ReplicaSets between the
        # instance_group autoscale bounds, scales idle models to zero,
        # and feeds shed directives back into admission. Its thread
        # starts lazily the first time an autoscale-enabled model is
        # loaded — servers without the config block pay nothing.
        self.autoscaler = autoscale.AutoscaleController(self)
        # Request-lifecycle cancellation (client_tpu.server.cancel):
        # every admitted request gets a CancelToken carrying its
        # deadline; transports cancel it on disconnect, the registry
        # routes explicit wire cancels (POST /v2/cancel/<id>) to it,
        # and every scheduler observes it at stage boundaries.
        # CLIENT_TPU_CANCEL=off disables minting (the cancel_overhead
        # bench A/B arm).
        self.cancel = cancel_mod.CancelRegistry()
        # Start stamps: tpu_server_info's uptime value (a scrape-level
        # restart detector) and the /v2/debug server section.
        self._started_wall = time.time()
        self._started_mono = time.monotonic()
        # Shared output fetcher for the direct/sequence paths
        # (client_tpu.server.fetch): all of a response's device->host
        # copies are issued at once and land in completion order, so
        # encode never serializes transfer-by-transfer. The dynamic
        # batcher owns its own fetcher (sized from the model's
        # fetch_pool_workers); this one covers everything that never
        # enters a batcher.
        self.fetcher = relay.OutputFetcher()
        # Ensemble stage-cache inserts serialize device outputs OFF the
        # request path on a single lazy worker (created on first
        # cacheable stage, torn down in shutdown): the dataflow hands
        # the next stage its device array immediately and the cache
        # copy materializes behind it.
        self._stage_insert_pool = None
        self._stage_insert_lock = threading.Lock()
        self._stats: Dict[str, _ModelStats] = {}
        self._stats_lock = threading.Lock()
        self._batchers: Dict[str, object] = {}
        self._batchers_lock = threading.Lock()
        # Sequence-batching schedulers, one per sequence model
        # (client_tpu.server.sequence), created lazily like batchers.
        self._sequencers: Dict[str, object] = {}
        self._sequencers_lock = threading.Lock()
        # Replica sets (client_tpu.server.replicas), one per
        # instance-group model, created lazily like batchers. The set's
        # proxy becomes the execution target of the model's scheduler
        # (batcher / sequencer / direct path), so every execution is
        # health-routed across N per-device fault domains.
        self._replica_sets: Dict[str, object] = {}
        self._replica_lock = threading.Lock()
        self._trace_settings: Dict[str, Dict[str, list]] = {"": {
            "trace_file": [""], "trace_level": ["OFF"], "trace_rate": ["1000"],
            "trace_count": ["-1"], "log_frequency": ["0"],
            "trace_mode": ["compact"],
        }}
        self._trace_state: Dict[str, dict] = {}
        self._trace_lock = threading.Lock()
        self._log_settings: Dict[str, object] = {
            "log_file": "", "log_info": True, "log_warning": True,
            "log_error": True, "log_verbose_level": 0, "log_format": "default",
        }
        self.ready = True
        # Names this core for scoped chaos injection: with several
        # in-process cores in one process (a fleet), chaos can degrade
        # ONE replica while the others stay healthy.
        self.chaos_scope: Optional[str] = None

    # -- health / metadata ----------------------------------------------

    def server_live(self) -> bool:
        return True

    def server_ready(self) -> bool:
        return self.ready

    def model_ready(self, name: str, version: str = "") -> bool:
        # Partial degradation keeps the model (and the server) ready:
        # readiness only flips when EVERY replica of an instance-group
        # model is ejected — one healthy fault domain still serves.
        if not self.repository.is_ready(name, version):
            return False
        with self._replica_lock:
            replica_set = self._replica_sets.get(name)
        return replica_set is None or replica_set.healthy_count() > 0

    def replica_health(self, name: str):
        """(healthy, total) for an instance-group model whose replica
        set is live, else None — the model-ready metadata both
        front-ends expose (x-replica-healthy/-total headers on HTTP,
        trailing metadata on gRPC)."""
        with self._replica_lock:
            replica_set = self._replica_sets.get(name)
        if replica_set is None:
            return None
        return replica_set.healthy_count(), replica_set.count

    def server_metadata(self) -> pb.ServerMetadataResponse:
        return pb.ServerMetadataResponse(
            name=SERVER_NAME, version=SERVER_VERSION, extensions=SERVER_EXTENSIONS
        )

    def model_metadata(self, name: str, version: str = "") -> pb.ModelMetadataResponse:
        return self.repository.get(name, version).metadata_pb()

    def model_config(self, name: str, version: str = "") -> pb.ModelConfigResponse:
        return pb.ModelConfigResponse(
            config=self.repository.get(name, version).config_pb()
        )

    # -- SLO engine wiring -----------------------------------------------

    def _slo_targets(self):
        """(name, SloTarget, model) for every ready model declaring an
        ``slo`` block — the set the burn-rate engine tracks."""
        out = []
        for model in self.repository.ready_models():
            target = sloengine.SloTarget.of(model)
            if target.declared():
                out.append((model.name, target, model))
        return out

    def _slo_collect(self, name: str,
                     target: sloengine.SloTarget) -> sloengine.SloSample:
        """One cumulative snapshot of the counters a burn computation
        differences: latency/TTFT good-vs-total from the always-on
        telemetry histograms (interpolated at the target bound),
        availability good-vs-bad from the model's success counters
        (errors, rejects, deadline expiries, and sheds all spend the
        budget)."""
        sample = sloengine.SloSample(0.0)
        telemetry = self.telemetry.for_model(name)
        if target.p99_latency_us:
            # With telemetry recording off, the histogram freezes and
            # burn would read 0 through a meltdown — flag the
            # objective unmonitorable so the verdict fails loudly.
            sample.latency_monitored = self.telemetry.enabled
            snap = telemetry.request.snapshot()
            sample.latency_total = float(snap["count"])
            sample.latency_good = sloengine.count_at_or_below(
                snap["buckets"], target.p99_latency_us)
        if target.ttft_p99_us:
            sample.ttft_monitored = self.telemetry.enabled
            snap = telemetry.stream_first.snapshot()
            sample.ttft_total = float(snap["count"])
            sample.ttft_good = sloengine.count_at_or_below(
                snap["buckets"], target.ttft_p99_us)
        if target.availability:
            stats = self._stats_for(name)
            with stats.lock:
                sample.ok_count = float(stats.success_count)
                # fail_count alone: every queue reject, deadline
                # expiry, shed, and plain error surfaces as a raised
                # exception that lands in fail_count exactly once —
                # adding the per-cause counters (rejected/timeout/
                # shed) on top would double-count those drops and
                # inflate burn ~2x. Tenant-quota rejects are absent
                # by design: they are POLICY signals (the client
                # exceeded its contract), not server availability —
                # the same stance the client breakers take
                # (status_map.QUOTA_REJECT_WIRE).
                sample.bad_count = float(stats.fail_count)
        return sample

    # -- statistics ------------------------------------------------------

    def _stats_for(self, name: str) -> _ModelStats:
        with self._stats_lock:
            if name not in self._stats:
                self._stats[name] = _ModelStats()
            return self._stats[name]

    def model_statistics(self, name: str = "", version: str = ""
                         ) -> pb.ModelStatisticsResponse:
        response = pb.ModelStatisticsResponse()
        models = (
            [self.repository.get(name, version)] if name
            else self.repository.ready_models()
        )
        # Evaluated BEFORE the per-model lock below: the collector
        # reads the same (non-reentrant) stats locks this loop holds.
        try:
            slo_verdicts = self.slo.evaluate()
        except Exception:  # noqa: BLE001 — statistics never take
            slo_verdicts = {}  # the server down
        for model in models:
            s = self._stats_for(model.name)
            with s.lock:
                stat = response.model_stats.add(
                    name=model.name,
                    version=model.version,
                    last_inference=s.last_inference_ms,
                    inference_count=s.inference_count,
                    execution_count=s.execution_count,
                    reject_count=s.rejected_count,
                    timeout_count=s.timeout_count,
                    cache_hit_count=s.cache_hit_count,
                    cache_miss_count=s.cache_miss_count,
                    shed_count=s.shed_count,
                )
                for level in sorted(s.priority_hist):
                    row = s.priority_hist[level]
                    stat.priority_stats.add(
                        priority_level=level, success_count=row[0],
                        reject_count=row[1], timeout_count=row[2],
                        shed_count=row[3], queue_ns=row[4])
                for tenant in sorted(s.tenant_hist):
                    row = s.tenant_hist[tenant]
                    stat.tenant_stats.add(
                        tenant=tenant, success_count=row[0],
                        reject_count=row[1], fail_count=row[2],
                        duration_ns=row[3])
                if s.stream_response_count or s.stream_count:
                    stream = stat.stream_stats
                    stream.stream_count = s.stream_count
                    stream.response_count = s.stream_response_count
                    stream.first_response.count = s.stream_first_count
                    stream.first_response.ns = s.stream_first_ns
                    stream.inter_response.count = s.stream_inter_count
                    stream.inter_response.ns = s.stream_inter_ns
                stat.inference_stats.cache_hit.count = s.cache_hit_count
                stat.inference_stats.cache_hit.ns = s.cache_hit_ns
                stat.inference_stats.cache_miss.count = s.cache_miss_count
                stat.inference_stats.cache_miss.ns = s.cache_miss_ns
                stat.inference_stats.success.count = s.success_count
                stat.inference_stats.success.ns = s.success_ns
                stat.inference_stats.fail.count = s.fail_count
                stat.inference_stats.fail.ns = s.fail_ns
                stat.inference_stats.queue.count = s.success_count
                stat.inference_stats.queue.ns = s.queue_ns
                stat.inference_stats.compute_input.count = s.success_count
                stat.inference_stats.compute_input.ns = s.compute_input_ns
                stat.inference_stats.compute_infer.count = s.success_count
                stat.inference_stats.compute_infer.ns = s.compute_infer_ns
                stat.inference_stats.compute_output.count = s.success_count
                stat.inference_stats.compute_output.ns = s.compute_output_ns
                for size in sorted(s.batch_hist):
                    count, compute_ns, fetch_ns = s.batch_hist[size]
                    row = stat.batch_stats.add(batch_size=size)
                    row.compute_infer.count = count
                    row.compute_infer.ns = compute_ns
                    row.compute_output.count = count
                    row.compute_output.ns = fetch_ns
            verdict = slo_verdicts.get(model.name)
            if verdict is not None:
                row = stat.slo_stats
                target = verdict["target"]
                row.p99_latency_target_us = target["p99_latency_us"]
                row.ttft_p99_target_us = target["ttft_p99_us"]
                row.availability_target = target["availability"]
                row.burn_rate_fast = verdict["burn"]["fast"]
                row.burn_rate_slow = verdict["burn"]["slow"]
                row.budget_remaining = verdict["budget_remaining"]
                row.healthy = verdict["healthy"]
            with self._batchers_lock:
                batcher = self._batchers.get(model.name)
            if batcher is not None:
                snap = batcher.stats_snapshot()
                pipe = stat.pipeline_stats
                pipe.pending_count = snap["pending_count"]
                pipe.inflight_count = snap["inflight_count"]
                pipe.queue_delay_us = snap["queue_delay_us"]
                pipe.compute_ns = snap["compute_ns"]
                pipe.fetch_ns = snap["fetch_ns"]
                pipe.overlap_ns = snap["overlap_ns"]
                pipe.overlap_ratio = snap["overlap_ratio"]
            with self._replica_lock:
                replica_set = self._replica_sets.get(model.name)
            if replica_set is not None:
                snap = replica_set.snapshot()
                stat.healthy_replicas = snap["healthy"]
                stat.total_replicas = snap["count"]
                for row in snap["replicas"]:
                    stat.replica_stats.add(
                        replica_index=row["index"],
                        healthy=row["healthy"],
                        request_count=row["requests"],
                        failure_count=row["failures"],
                        execution_count=row["execution_count"],
                        exec_ns=row["exec_ns"],
                        ejected_count=row["ejected_count"],
                        readmitted_count=row["readmitted_count"])
            device = self.devstats.model_device_snapshot(model.name)
            if device is not None:
                row = stat.device_stats
                row.hbm_bytes = device["hbm_bytes"]
                for component, nbytes in device["components"]:
                    row.components.add(component=component,
                                       hbm_bytes=nbytes)
                row.compile_count = device["compile_count"]
                row.compile_ns = device["compile_ns"]
            with self._sequencers_lock:
                sequencer = self._sequencers.get(model.name)
            if sequencer is not None:
                snap = sequencer.stats_snapshot()
                seq = stat.sequence_stats
                seq.active_sequences = snap["active_sequences"]
                seq.slot_total = snap["slot_total"]
                seq.backlog_depth = snap["backlog_depth"]
                seq.idle_reclaimed_total = snap["idle_reclaimed_total"]
                seq.sequences_started = snap["sequences_started"]
                seq.sequences_completed = snap["sequences_completed"]
                seq.step_count = snap["step_count"]
                seq.fused_steps = snap["fused_steps"]
        return response

    def metrics_text(self, openmetrics: bool = False) -> str:
        """Prometheus exposition text (parity: the Triton /metrics
        endpoint that perf MetricsManager scrapes, metrics_manager.h:56;
        the DCGM GPU gauges map to TPU HBM gauges here).

        ``openmetrics=True`` renders the OpenMetrics flavor a scraper
        negotiates via ``Accept: application/openmetrics-text``:
        trace-id exemplars on histogram buckets plus the ``# EOF``
        terminator. The default text-format-0.0.4 flavor NEVER carries
        exemplars — stock Prometheus rejects them outside OpenMetrics,
        and a rejected line drops the whole scrape."""
        lines = []

        def family(name, kind, help_text, rows):
            if not rows:
                return
            lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)

        success, failure, count, exec_count, duration = [], [], [], [], []
        fused_hist, rejected, timed_out = [], [], []
        cache_hits, cache_misses = [], []
        shed_rows = []
        cancelled_rows, wasted_rows = [], []
        tenant_totals: Dict[str, list] = {}
        with self._stats_lock:
            stats_snapshot = dict(self._stats)
        for name, s in sorted(stats_snapshot.items()):
            label = '{model="%s",version="1"}' % name
            with s.lock:
                success.append("nv_inference_request_success%s %d"
                               % (label, s.success_count))
                failure.append("nv_inference_request_failure%s %d"
                               % (label, s.fail_count))
                count.append("nv_inference_count%s %d"
                             % (label, s.inference_count))
                exec_count.append("nv_inference_exec_count%s %d"
                                  % (label, s.execution_count))
                duration.append("nv_inference_request_duration_us%s %d"
                                % (label, (s.success_ns + s.fail_ns) // 1000))
                rejected.append("tpu_request_rejected_total%s %d"
                                % (label, s.rejected_count))
                timed_out.append("tpu_request_timeout_total%s %d"
                                 % (label, s.timeout_count))
                cache_hits.append("tpu_cache_hit_total%s %d"
                                  % (label, s.cache_hit_count))
                cache_misses.append("tpu_cache_miss_total%s %d"
                                    % (label, s.cache_miss_count))
                for size in sorted(s.batch_hist):
                    fused_hist.append(
                        'tpu_batch_fused_total{model="%s",size="%d"} %d'
                        % (name, size, s.batch_hist[size][0]))
                for stage in sorted(s.cancelled_hist):
                    cancelled_rows.append(
                        'tpu_request_cancelled_total{model="%s",'
                        'stage="%s"} %d'
                        % (name, stage, s.cancelled_hist[stage]))
                wasted_rows.append(
                    'tpu_wasted_compute_us{model="%s"} %d'
                    % (name, s.wasted_compute_ns // 1000))
                for level in sorted(s.priority_hist):
                    shed_rows.append(
                        'tpu_shed_total{model="%s",priority="%d"} %d'
                        % (name, level, s.priority_hist[level][3]))
                for tenant, row in s.tenant_hist.items():
                    total = tenant_totals.setdefault(tenant, [0, 0, 0, 0])
                    for i in range(4):
                        total[i] += row[i]
        family("nv_inference_request_success", "counter",
               "Number of successful inference requests", success)
        family("nv_inference_request_failure", "counter",
               "Number of failed inference requests", failure)
        family("nv_inference_count", "counter",
               "Number of inferences performed", count)
        family("nv_inference_exec_count", "counter",
               "Number of model executions performed", exec_count)
        family("nv_inference_request_duration_us", "counter",
               "Cumulative inference request duration", duration)
        family("tpu_batch_fused_total", "counter",
               "Fused executions per executed batch size", fused_hist)
        family("tpu_request_rejected_total", "counter",
               "Requests rejected by queue-policy admission control "
               "(max_queue_size)", rejected)
        family("tpu_request_timeout_total", "counter",
               "Requests expired by their queue deadline before "
               "dispatch", timed_out)
        family("tpu_cache_hit_total", "counter",
               "Requests served from the response cache (incl. "
               "single-flight followers)", cache_hits)
        family("tpu_cache_miss_total", "counter",
               "Cache-eligible requests that executed the model",
               cache_misses)
        family("tpu_shed_total", "counter",
               "Requests dropped by graceful load shedding, "
               "lowest-priority-first (displacement at a full queue + "
               "watermark sheds)", shed_rows)
        family("tpu_request_cancelled_total", "counter",
               "Requests abandoned per stage boundary (client "
               "disconnect, wire cancel, hedge loser, post-dispatch "
               "deadline expiry)", cancelled_rows)
        family("tpu_wasted_compute_us", "counter",
               "Device compute spent on requests already cancelled at "
               "completion (work nobody read)", wasted_rows)

        # Server identity + uptime: the value resets to ~0 on restart,
        # so a scrape-side `resets()`/drop detector catches process
        # churn that per-model counters (which also reset) only imply.
        family("tpu_server_info", "gauge",
               "Server identity labels (name/version); value = uptime "
               "in seconds, so a drop between scrapes means a restart",
               ['tpu_server_info{name="%s",version="%s"} %d'
                % (SERVER_NAME, SERVER_VERSION,
                   int(time.monotonic() - self._started_mono))])

        tenant_success, tenant_rejected, tenant_failure = [], [], []
        # Quota rejects come from the quota manager when configured —
        # it counts every reject, including ones for model names that
        # never minted a stats entry; per-model rows are the fallback.
        quota_snapshot = (self.tenant_quotas.snapshot()
                          if self.tenant_quotas is not None else None)
        if quota_snapshot is not None:
            rejected_by_tenant = {
                tenant: snap["rejected"]
                for tenant, snap in quota_snapshot.items()}
        else:
            rejected_by_tenant = {
                tenant: row[1] for tenant, row in tenant_totals.items()}
        for tenant in sorted(tenant_totals):
            row = tenant_totals[tenant]
            label = '{tenant="%s"}' % _escape_label_value(tenant)
            tenant_success.append("tpu_tenant_success_total%s %d"
                                  % (label, row[0]))
            tenant_failure.append("tpu_tenant_failure_total%s %d"
                                  % (label, row[2]))
        for tenant in sorted(rejected_by_tenant):
            tenant_rejected.append(
                'tpu_tenant_rejected_total{tenant="%s"} %d'
                % (_escape_label_value(tenant),
                   rejected_by_tenant[tenant]))
        family("tpu_tenant_success_total", "counter",
               "Successful requests per tenant (summed over models)",
               tenant_success)
        family("tpu_tenant_rejected_total", "counter",
               "Requests rejected by per-tenant quotas (token bucket "
               "or concurrency cap)", tenant_rejected)
        family("tpu_tenant_failure_total", "counter",
               "Failed requests per tenant (post-admission errors)",
               tenant_failure)
        # tpu_tenant_request_duration_us is emitted as a HISTOGRAM by
        # the telemetry registry below (the sum-only counter this used
        # to be gave rate() nothing to divide by).

        tenant_inflight, tenant_tokens = [], []
        if quota_snapshot is not None:
            for tenant, snap in sorted(quota_snapshot.items()):
                label = '{tenant="%s"}' % _escape_label_value(tenant)
                tenant_inflight.append("tpu_tenant_inflight%s %d"
                                       % (label, snap["inflight"]))
                tenant_tokens.append("tpu_tenant_tokens%s %.3f"
                                     % (label, snap["tokens"]))
        family("tpu_tenant_inflight", "gauge",
               "Requests currently in flight per tenant",
               tenant_inflight)
        family("tpu_tenant_tokens", "gauge",
               "Tokens remaining in each tenant's admission bucket",
               tenant_tokens)

        size_rows, entry_rows, evict_rows = [], [], []
        for name, snap in sorted(self.response_cache.snapshot().items()):
            label = '{model="%s"}' % name
            size_rows.append("tpu_cache_size_bytes%s %d"
                             % (label, snap["bytes"]))
            entry_rows.append("tpu_cache_entries%s %d"
                              % (label, snap["entries"]))
            evict_rows.append("tpu_cache_evictions_total%s %d"
                              % (label, snap["evictions"]))
        family("tpu_cache_size_bytes", "gauge",
               "Bytes of cached responses held per model (the server-"
               "level byte budget is shared across models)", size_rows)
        family("tpu_cache_entries", "gauge",
               "Cached responses held per model", entry_rows)
        family("tpu_cache_evictions_total", "counter",
               "Responses evicted by the LRU byte budget", evict_rows)

        pending_rows, inflight_rows, delay_rows, overlap_rows = \
            [], [], [], []
        queue_rows, priority_queue_rows = [], []
        with self._batchers_lock:
            batchers_snapshot = dict(self._batchers)
        for name, batcher in sorted(batchers_snapshot.items()):
            try:
                snap = batcher.stats_snapshot()
            except Exception:  # noqa: BLE001 — metrics never take
                continue  # the server down
            label = '{model="%s"}' % name
            for level in sorted(snap.get("pending_by_priority", {})):
                priority_queue_rows.append(
                    'tpu_priority_queue_size{model="%s",priority="%d"} '
                    '%d' % (name, level,
                            snap["pending_by_priority"][level]))
            # Deliberately the same sample as tpu_batch_pending_depth:
            # tpu_queue_size is the stable queue-policy-facing name
            # (paired with tpu_request_rejected_total); the batch_*
            # family stays for PR 1 dashboards.
            queue_rows.append("tpu_queue_size%s %d"
                              % (label, snap["pending_count"]))
            pending_rows.append("tpu_batch_pending_depth%s %d"
                                % (label, snap["pending_count"]))
            inflight_rows.append("tpu_batch_inflight%s %d"
                                 % (label, snap["inflight_count"]))
            delay_rows.append("tpu_batch_queue_delay_us%s %d"
                              % (label, snap["queue_delay_us"]))
            overlap_rows.append("tpu_batch_overlap_ratio%s %.6f"
                                % (label, snap["overlap_ratio"]))
        family("tpu_queue_size", "gauge",
               "Requests pending in the per-model scheduler queue "
               "(admission-controlled by max_queue_size)", queue_rows)
        family("tpu_priority_queue_size", "gauge",
               "Requests pending per priority class (1 = highest) in "
               "the per-model scheduler queue", priority_queue_rows)
        family("tpu_batch_pending_depth", "gauge",
               "Requests waiting in the dynamic batcher's bucket queues",
               pending_rows)
        family("tpu_batch_inflight", "gauge",
               "Fused batches currently in the compute/fetch pipeline",
               inflight_rows)
        family("tpu_batch_queue_delay_us", "gauge",
               "Current adaptive max queue delay", delay_rows)
        family("tpu_batch_overlap_ratio", "gauge",
               "Fraction of output-fetch time with other batches' "
               "compute or fetch in flight", overlap_rows)

        active_rows, slots_rows, backlog_rows, reclaimed_rows = \
            [], [], [], []
        with self._sequencers_lock:
            sequencers_snapshot = dict(self._sequencers)
        for name, sequencer in sorted(sequencers_snapshot.items()):
            try:
                snap = sequencer.stats_snapshot()
            except Exception:  # noqa: BLE001 — metrics never take
                continue  # the server down
            label = '{model="%s"}' % name
            active_rows.append("tpu_sequence_active%s %d"
                               % (label, snap["active_sequences"]))
            slots_rows.append("tpu_sequence_slots%s %d"
                              % (label, snap["slot_total"]))
            backlog_rows.append("tpu_sequence_backlog%s %d"
                                % (label, snap["backlog_depth"]))
            reclaimed_rows.append(
                "tpu_sequence_idle_reclaimed_total%s %d"
                % (label, snap["idle_reclaimed_total"]))
        family("tpu_sequence_active", "gauge",
               "Sequences currently holding a scheduler slot",
               active_rows)
        # Renamed from tpu_sequence_slots_total (PR 3): the _total
        # suffix implies a counter to Prometheus tooling, but this is
        # a configured-capacity gauge — metrics_lint enforces the
        # convention now.
        family("tpu_sequence_slots", "gauge",
               "Configured candidate-sequence slots", slots_rows)
        family("tpu_sequence_backlog", "gauge",
               "Sequence starts waiting for a free slot", backlog_rows)
        family("tpu_sequence_idle_reclaimed_total", "counter",
               "Sequence slots reclaimed by the idle timeout "
               "(max_sequence_idle_microseconds)", reclaimed_rows)

        healthy_rows, replica_total_rows = [], []
        ejected_rows, readmitted_rows, redispatch_rows = [], [], []
        exec_rows, slice_rows = [], []
        with self._replica_lock:
            replica_snapshot = dict(self._replica_sets)
        for name, replica_set in sorted(replica_snapshot.items()):
            try:
                snap = replica_set.snapshot()
            except Exception:  # noqa: BLE001 — metrics never take
                continue  # the server down
            label = '{model="%s"}' % name
            healthy_rows.append("tpu_replica_healthy%s %d"
                                % (label, snap["healthy"]))
            replica_total_rows.append("tpu_replica_count%s %d"
                                      % (label, snap["count"]))
            ejected_rows.append("tpu_replica_ejected_total%s %d"
                                % (label, snap["ejections"]))
            readmitted_rows.append("tpu_replica_readmitted_total%s %d"
                                   % (label, snap["readmissions"]))
            redispatch_rows.append("tpu_replica_redispatch_total%s %d"
                                   % (label, snap["redispatches"]))
            for row in snap["replicas"]:
                exec_rows.append(
                    'tpu_replica_exec_us{model="%s",replica="%d"} %d'
                    % (name, row["index"], row["exec_ns"] // 1000))
                if snap.get("sharded"):
                    slice_rows.append(
                        'tpu_slice_healthy{model="%s",slice="%d"} %d'
                        % (name, row["index"],
                           1 if row["healthy"] else 0))
        family("tpu_replica_healthy", "gauge",
               "Healthy replicas (fault domains) currently in routing "
               "per instance-group model", healthy_rows)
        family("tpu_replica_count", "gauge",
               "Configured replicas per instance-group model",
               replica_total_rows)
        family("tpu_replica_ejected_total", "counter",
               "Replica ejections (watchdog trips + circuit-breaker "
               "opens) per model", ejected_rows)
        family("tpu_replica_readmitted_total", "counter",
               "Replicas readmitted by the self-healing supervisor "
               "after a re-initialize + canary probe", readmitted_rows)
        family("tpu_replica_redispatch_total", "counter",
               "Batches re-dispatched to a healthy sibling after a "
               "replica failure (bounded: once per batch)",
               redispatch_rows)
        family("tpu_replica_exec_us", "counter",
               "Cumulative successful execution time per replica",
               exec_rows)
        family("tpu_slice_healthy", "gauge",
               "Per-slice health for mesh-sharded instance groups "
               "(1 = the slice's whole device set is in routing; one "
               "sick chip zeroes its slice, siblings stay 1)",
               slice_rows)

        desired_rows, scale_event_rows, replica_second_rows = [], [], []
        for name, entry in sorted(self.autoscaler.snapshot().items()):
            label = '{model="%s"}' % name
            desired_rows.append("tpu_replica_desired%s %d"
                                % (label, entry["desired"]))
            replica_second_rows.append(
                "tpu_replica_seconds_total%s %.3f"
                % (label, entry["replica_seconds"]))
            for key, count in sorted(entry["events"].items()):
                direction, reason = key.split("|", 1)
                scale_event_rows.append(
                    'tpu_scale_events_total{model="%s",direction="%s"'
                    ',reason="%s"} %d'
                    % (name, direction, reason, count))
        family("tpu_replica_desired", "gauge",
               "Replicas the autoscale controller currently wants per "
               "model (actual converges via canaried scale-up / "
               "drained scale-down)", desired_rows)
        family("tpu_scale_events_total", "counter",
               "Autoscale decisions per model by direction (up/down/"
               "shed/shed_clear) and reason", scale_event_rows)
        family("tpu_replica_seconds_total", "counter",
               "Replica-seconds consumed per model (fleet size "
               "integrated over time — the autoscaler's cost metric)",
               replica_second_rows)

        kv_used_rows, kv_total_rows = [], []
        kv_hit_rows, prefill_rows = [], []
        for model in self.repository.ready_models():
            stats_fn = getattr(model, "kv_stats", None)
            if stats_fn is None:
                continue
            try:
                snap = stats_fn()
            except Exception:  # noqa: BLE001 — metrics never take
                continue  # the server down
            if not snap:
                continue  # dense A/B arm: no paged pool to report
            label = '{model="%s"}' % model.name
            kv_used_rows.append("tpu_kv_pages_used%s %d"
                                % (label, snap["pages_used"]))
            kv_total_rows.append("tpu_kv_pages_total%s %d"
                                 % (label, snap["pages_total"]))
            kv_hit_rows.append("tpu_kv_prefix_hits_total%s %d"
                               % (label, snap["prefix_hits_total"]))
            prefill_rows.append("tpu_prefill_chunks_total%s %d"
                                % (label, snap["prefill_chunks_total"]))
        family("tpu_kv_pages_used", "gauge",
               "Paged-KV-cache pages held by live decode lanes "
               "(private pages + shared prefix pages pinned by a "
               "lane; prefix-cache-only pages are evictable and not "
               "counted)", kv_used_rows)
        family("tpu_kv_pages_total", "gauge",
               "Configured paged-KV-cache page-pool capacity",
               kv_total_rows)
        family("tpu_kv_prefix_hits_total", "counter",
               "Prompt pages served from the shared prefix cache "
               "(content-hashed full pages, copy-on-write) instead of "
               "being prefilled", kv_hit_rows)
        family("tpu_prefill_chunks_total", "counter",
               "LLM prefill dispatches (bounded chunked-prefill "
               "chunks + batched short-prompt prefills)", prefill_rows)

        # Device-axis families (client_tpu.server.devstats): the
        # tpu_hbm_* gauges plus the per-model HBM ledger, busy-time/
        # duty-cycle counters, and compile telemetry. Scrape failures
        # are counted (tpu_device_stats_errors_total) and logged once
        # per process — the old inline block swallowed them silently.
        try:
            lines.extend(self.devstats.render_metrics())
        except Exception:  # noqa: BLE001 — metrics never take
            pass  # the server down
        # Allocator families (client_tpu.server.hbm): per-device free
        # bytes against the managed budget, eviction counters by
        # victim/reason, weight page-out counts, restore-latency
        # histogram.
        try:
            lines.extend(self.hbm.render_metrics())
        except Exception:  # noqa: BLE001 — metrics never take
            pass  # the server down
        # SLO families (tpu_slo_target / _burn_rate / _budget_remaining
        # / _healthy): rendered by the engine, empty when no ready
        # model declares an `slo` block. Rendering evaluates — the
        # scrape itself advances the burn-rate windows, so a server
        # that is only ever scraped still computes fresh verdicts.
        try:
            lines.extend(self.slo.render())
        except Exception:  # noqa: BLE001 — metrics never take
            pass  # the server down
        # Latency-histogram + streaming-token families (request/stage
        # durations, stream TTFT/ITL, per-tenant duration histogram) —
        # HELP/TYPE lines come with the rendered block. Exemplar
        # suffixes are OpenMetrics syntax, gated on the scraper's
        # negotiated flavor, never on server state.
        lines.extend(self.telemetry.render(
            escape=_escape_label_value, exemplars=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- live introspection (GET /v2/debug) ------------------------------

    def debug_snapshot(self, model_name: str = "") -> dict:
        """One JSON-able snapshot of everything an operator asks
        "why is this slow RIGHT NOW" about: queue depth per
        bucket/priority, in-flight requests with age and current span
        stage, replica health/breaker states, KV page-pool occupancy,
        arena/shm usage, SLO verdicts, flight-ring occupancy, and
        chaos counters. ``model_name`` restricts the model-keyed
        sections. Served by GET /v2/debug on both HTTP front-ends and
        the inference.Debug gRPC surface; every collection here is
        cardinality-bounded (tools/metrics_lint.lint_debug_snapshot
        gates that in CI)."""

        def wanted(name: str) -> bool:
            return not model_name or name == model_name

        doc: dict = {
            "server": {
                "name": SERVER_NAME,
                "version": SERVER_VERSION,
                "ready": bool(self.ready),
                "uptime_s": round(
                    time.monotonic() - self._started_mono, 3),
                "started_at": self._started_wall,
            },
            "models": [],
            "queues": {},
            "sequencers": {},
            "in_flight": [
                entry for entry in self.flight.in_flight()
                if wanted(entry["model"])
            ],
            "replicas": {},
            "kv_pools": {},
            "cache": {},
            "slo": {},
            "flight": {},
            "chaos": chaos.stats(),
            "controller": {
                name: entry
                for name, entry in self.autoscaler.snapshot().items()
                if wanted(name)
            },
        }
        try:
            # Device axis: HBM ledger rows, busy/duty per device,
            # compile counts, profiler state (docs/
            # device_observability.md). Process-global, so the section
            # is identical across in-process cores.
            doc["devices"] = self.devstats.debug_snapshot()
        except Exception:  # noqa: BLE001 — introspection never takes
            pass  # the server down
        try:
            # HBM allocator: per-device capacity/free, leases by
            # model/component with idle age, the paged-out set,
            # eviction history, and arbitration queue depth
            # (docs/hbm.md) — eviction incidents are introspectable
            # like everything else.
            doc["hbm"] = self.hbm.debug_snapshot()
        except Exception:  # noqa: BLE001 — introspection never takes
            pass  # the server down
        for model in self.repository.ready_models():
            if not wanted(model.name):
                continue
            doc["models"].append({
                "name": model.name,
                "version": model.version,
                "ready": self.model_ready(model.name),
            })
            stats_fn = getattr(model, "kv_stats", None)
            if stats_fn is not None:
                try:
                    snap = stats_fn()
                except Exception:  # noqa: BLE001 — introspection
                    snap = None  # never takes the server down
                if snap:
                    doc["kv_pools"][model.name] = snap
        with self._batchers_lock:
            batchers = dict(self._batchers)
        for name, batcher in sorted(batchers.items()):
            if not wanted(name):
                continue
            try:
                doc["queues"][name] = batcher.debug_snapshot()
            except Exception:  # noqa: BLE001
                continue
        with self._sequencers_lock:
            sequencers = dict(self._sequencers)
        for name, sequencer in sorted(sequencers.items()):
            if not wanted(name):
                continue
            try:
                doc["sequencers"][name] = sequencer.stats_snapshot()
            except Exception:  # noqa: BLE001
                continue
        with self._replica_lock:
            replica_sets = dict(self._replica_sets)
        for name, replica_set in sorted(replica_sets.items()):
            if not wanted(name):
                continue
            try:
                doc["replicas"][name] = replica_set.snapshot()
            except Exception:  # noqa: BLE001
                continue
        for name, snap in sorted(self.response_cache.snapshot().items()):
            if wanted(name):
                doc["cache"][name] = snap
        try:
            verdicts = self.slo.evaluate()
        except Exception:  # noqa: BLE001
            verdicts = {}
        doc["slo"] = {name: verdict for name, verdict in verdicts.items()
                      if wanted(name)}
        doc["flight"] = {name: snap
                         for name, snap in self.flight.stats().items()
                         if wanted(name)}
        if self.tenant_quotas is not None:
            try:
                doc["tenants"] = self.tenant_quotas.snapshot()
            except Exception:  # noqa: BLE001
                pass
        arena = self.memory.arena
        if arena is not None:
            try:
                regions = arena.list_regions()
                doc["arena"] = {
                    "regions": len(regions),
                    "bytes_total": sum(r[2] for r in regions),
                }
            except Exception:  # noqa: BLE001
                pass
        try:
            status = self.memory.system_status("")
            doc["shm"] = {
                "system": [
                    {"name": r.name, "byte_size": int(r.byte_size)}
                    for r in status.regions.values()
                ],
            }
            status = self.memory.tpu_status("")
            doc["shm"]["tpu"] = [
                {"name": r.name, "device_id": int(r.device_id),
                 "byte_size": int(r.byte_size)}
                for r in status.regions.values()
            ]
        except Exception:  # noqa: BLE001
            pass
        return doc

    def debug_profile(self, duration_ms: int = 500,
                      model_name: str = "") -> dict:
        """On-demand bounded profiler capture (GET /v2/debug/profile
        on both HTTP front-ends + /inference.Debug/Profile): starts a
        jax.profiler trace when the platform supports one and always
        writes a span-derived chrome trace of the same window under a
        server-owned directory; concurrent captures coalesce
        single-flight. Returns paths + a summary."""
        return self.devstats.profiler.capture(duration_ms, model_name)

    def debug_flight(self, model_name: str = "") -> dict:
        """The flight-ring dump (GET /v2/debug/flight?model=M): kept
        anomaly traces with full span trees, oldest first."""
        return {
            "stats": {
                name: snap
                for name, snap in self.flight.stats().items()
                if not model_name or name == model_name
            },
            "records": self.flight.snapshot(model_name or None),
        }

    # -- trace / log settings -------------------------------------------

    def _effective_trace_settings(self, model_name: str) -> Dict[str, list]:
        return self._trace_settings.get(model_name) \
            or self._trace_settings[""]

    def trace_setting(self, model_name: str, updates: Dict[str, list]
                      ) -> Dict[str, list]:
        with self._trace_lock:
            if not updates:
                # Pure read: snapshotting per-model settings here
                # (setdefault) would freeze this model against later
                # global updates — a get must not change what a future
                # update_trace_settings("") applies to.
                return dict(self._effective_trace_settings(model_name))
            # Flush every buffered state under its PRE-update settings
            # (so records land in the file they were recorded for),
            # then re-arm the sampling counters of the states the
            # updated key governs (Triton re-arms trace_count on
            # settings updates).
            for name, state in self._trace_state.items():
                if state["buffer"]:
                    self._flush_trace(
                        name, self._effective_trace_settings(name),
                        state)
            settings = self._trace_settings.setdefault(
                model_name, dict(self._trace_settings[""])
            )
            for key, value in updates.items():
                if not value:  # clear -> revert to global
                    settings[key] = list(
                        self._trace_settings[""].get(key, []))
                else:
                    settings[key] = [str(v) for v in value]
            for name, state in self._trace_state.items():
                governed = name == model_name or (
                    model_name == "" and name not in self._trace_settings)
                if governed:
                    state["seen"] = 0
                    state["emitted"] = 0
        return settings

    def _trace_state_for(self, model_name: str) -> dict:
        """Per-model sampling state (caller holds _trace_lock)."""
        return self._trace_state.setdefault(
            model_name, {"seen": 0, "emitted": 0, "next_id": 1,
                         "buffer": []})

    def _trace_begin(self, model_name: str, trace_context: Optional[str],
                     request_id: str
                     ) -> Optional[spantrace.RequestTrace]:
        """Sampling decision for one request (Triton trace semantics:
        trace_level != OFF enables, trace_rate samples 1-in-N,
        trace_count caps). Runs at request START so every stage —
        cache hits and single-flight waits included — lands in the
        span tree; the trace_count slot is reserved here so a settings
        update's re-arm keeps exact counts. Returns None (the
        near-zero-cost path) for unsampled requests."""
        settings = self._effective_trace_settings(model_name)
        level = (settings.get("trace_level") or ["OFF"])[0]
        if level in ("", "OFF"):
            return None
        if not (settings.get("trace_file") or [""])[0]:
            # No sink configured: tracing stays off (Triton needs an
            # explicit trace file too; an implicit cwd-relative
            # default would litter the server's working directory).
            return None
        try:
            rate = max(1, int((settings.get("trace_rate") or ["1000"])[0]))
            cap = int((settings.get("trace_count") or ["-1"])[0])
        except ValueError:
            return None
        with self._trace_lock:
            state = self._trace_state_for(model_name)
            state["seen"] += 1
            if (state["seen"] - 1) % rate != 0:
                return None
            if 0 <= cap <= state["emitted"]:
                return None
            state["emitted"] += 1
        return spantrace.RequestTrace(
            trace_context,
            attrs={"model": model_name, "request_id": request_id})

    def _trace_emit(self, model_name: str, request_id: str,
                    trace: spantrace.RequestTrace) -> None:
        """Buffers one finished trace under the model's CURRENT
        settings (trace_mode selects the rendering, log_frequency
        batches file writes); a later settings update flushes earlier
        buffers under their pre-update settings (trace_setting)."""
        settings = self._effective_trace_settings(model_name)
        try:
            freq = int((settings.get("log_frequency") or ["0"])[0])
        except ValueError:
            freq = 0
        mode = (settings.get("trace_mode") or ["compact"])[0]
        if mode not in spantrace.TRACE_MODES:
            mode = "compact"
        with self._trace_lock:
            state = self._trace_state_for(model_name)
            record_id = state["next_id"]
            state["next_id"] += 1
        # Rendering runs OUTSIDE the lock: at trace_rate=1 every
        # request emits, and serializing dict/JSON assembly on the
        # shared lock would put tracing itself on the critical path
        # (file order may interleave across threads; readers sort by
        # timestamp, ids stay unique).
        if mode == "chrome":
            payload = spantrace.chrome_events(
                trace, record_id, model_name, request_id)
        else:
            payload = spantrace.compact_record(
                trace, record_id, model_name, request_id)
        with self._trace_lock:
            state = self._trace_state_for(model_name)
            state["buffer"].append((mode, payload))
            if len(state["buffer"]) >= max(1, freq):
                self._flush_trace(model_name, settings, state)

    def _flush_trace(self, model_name: str, settings: Dict[str, list],
                     state: dict) -> None:
        """Appends buffered records to the settings' trace_file
        (caller holds _trace_lock): compact records as JSON lines,
        chrome events as an open JSON array — the Chrome trace format
        explicitly allows the missing close bracket, so the file loads
        in chrome://tracing and ui.perfetto.dev as written."""
        import json as _json
        import os as _os

        path = (settings.get("trace_file") or [""])[0]
        records, state["buffer"] = state["buffer"], []
        if not path:
            return  # sink was never configured; drop silently
        try:
            fresh = not _os.path.exists(path) or _os.path.getsize(path) == 0
            with open(path, "a") as f:
                for mode, payload in records:
                    if mode == "chrome":
                        if fresh:
                            f.write("[\n")
                            fresh = False
                        for event in payload:
                            f.write(_json.dumps(event) + ",\n")
                    else:
                        f.write(_json.dumps(payload) + "\n")
        except OSError:
            pass  # tracing must never fail the request path

    def log_settings(self, updates: Dict[str, object]) -> Dict[str, object]:
        for key, value in updates.items():
            self._log_settings[key] = value
        return dict(self._log_settings)

    # -- repository control ---------------------------------------------

    def repository_index(self, ready_only: bool = False
                         ) -> pb.RepositoryIndexResponse:
        return self.repository.index(ready_only)

    def load_model(self, name: str, warmup: bool = True) -> None:
        # A paged-out model "loads" by restoring its weights — the
        # instance never left the repository, so the factory/warmup
        # round-trip (and a second ledger measurement) would be waste.
        if self.restore_model(name):
            return
        # The load (and its warmup compiles) runs inside a device-
        # ledger measurement: the per-device memory_stats() delta —
        # cross-checked against the instance's exact jax.Array nbytes
        # — becomes the model's `weights` HBM row, and warmup compiles
        # attribute to the model instead of `unattributed`.
        with self.devstats.measure_model_load(name) as measure:
            model = self.repository.load(name)
            measure.model = model
            if warmup:
                model.warmup()
        # The allocator adopts the measured weights row: the lease
        # charges the device budget post-hoc and rebalance pages out
        # colder models if this admission overflowed it.
        try:
            self.hbm.adopt_weights(
                model, measure.row,
                on_page_out=lambda: self._quiesce_model(name),
                on_restore=lambda: self._unquiesce_model(name))
        except Exception:  # noqa: BLE001 — accounting must never
            _LOG.warning("hbm: weights adoption failed for %s",  # block
                        name, exc_info=True)
        if autoscale.AutoscaleController.config_of(model) is not None:
            self.autoscaler.ensure_started()

    def _stop_schedulers(self, name: str) -> None:
        """Stops a model's sequencer, batcher, and replica set (in
        that order — the batcher's stop() drains its queued tail
        through the replica router) and flushes buffered traces.
        Shared by the unload teardown and the weight page-out
        quiesce."""
        with self._sequencers_lock:
            sequencer = self._sequencers.pop(name, None)
        if sequencer is not None:
            sequencer.stop()
        with self._batchers_lock:
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.stop()
        # Replica sets drain AFTER the schedulers: the batcher's
        # stop() executes its queued tail through the replica
        # router, so the per-device queues must still be routing
        # while it drains.
        with self._replica_lock:
            replica_set = self._replica_sets.pop(name, None)
        if replica_set is not None:
            replica_set.stop()
        with self._trace_lock:
            state = self._trace_state.get(name)
            if state is not None and state["buffer"]:
                self._flush_trace(
                    name, self._effective_trace_settings(name), state)

    def unload_model(self, name: str) -> None:
        # Graceful drain ordering: (1) shed NEW requests (503/
        # UNAVAILABLE + Retry-After) before anything stops, (2) stop
        # the schedulers — their stop() drains queued work, which still
        # holds in-flight counts, (3) wait for in-flight to hit zero
        # (bounded) and only then tear the model down.
        self.repository.begin_unload(name)
        try:
            self._stop_schedulers(name)
        finally:
            # begin_unload flipped the model UNAVAILABLE; finish MUST
            # run even when a scheduler's stop() raises, or the model
            # is stuck draining forever — shedding every request with
            # 503 while its instance and device memory stay resident
            # (tpulint: resource-pairing found the unprotected span).
            self.repository.finish_unload(name)
            # Every lease dies with the instance — device bytes,
            # paged-out host copies, and the underlying ledger rows
            # (the allocator sweeps its own rows; release_model below
            # still sweeps anything a crashed teardown left behind —
            # an unloaded model must leave no HBM attribution
            # residue).
            try:
                self.hbm.release_model(name)
            except Exception:  # noqa: BLE001 — teardown must not raise
                _LOG.warning("hbm: lease sweep failed for %s", name,
                            exc_info=True)
            self.devstats.ledger.release_model(name)

    # -- weight paging (client_tpu.server.hbm) ---------------------------

    def _quiesce_model(self, name: str) -> None:
        """Pre-page-out callback run by the allocator (eviction or
        scale-to-zero): stop admitting, stop the schedulers, drain
        in-flight — the weights must not move mid-request. Never
        raises (it runs inside the allocator's arbitration)."""
        try:
            # tpulint: disable=resource-pairing -- the drain state IS
            # the paged-out model's admission gate: it is deliberately
            # held until _unquiesce_model's mark_ready at restore (or
            # unload_model's finish_unload if the model is torn down
            # cold), so no release belongs in this function
            self.repository.begin_unload(name)
            self._stop_schedulers(name)
            if not self.repository.drain(
                    name, drain_timeout_s=hbm_mod.EVICT_DRAIN_TIMEOUT_S,
                    reason="weights paged out to host; restoring on "
                           "next arrival"):
                _LOG.warning("hbm: %s still had requests in flight at "
                            "page-out drain deadline; paging out "
                            "anyway (host copies keep it correct, "
                            "just slow)", name)
        except Exception:  # noqa: BLE001
            _LOG.warning("hbm: quiesce failed for %s", name,
                        exc_info=True)

    def _unquiesce_model(self, name: str) -> None:
        """Post-restore callback: weights are device-resident again,
        re-admit traffic."""
        try:
            self.repository.mark_ready(name)
        except Exception:  # noqa: BLE001
            _LOG.warning("hbm: mark_ready failed for %s", name,
                        exc_info=True)

    def page_out_model(self, name: str) -> Optional[dict]:
        """Scale-to-zero page-out: moves a pageable model's weights
        to host (ledger rows move to the paged_out side table) and
        leaves the instance registered-but-unavailable. None when the
        model has no pageable resident weights — the caller falls
        back to a full unload."""
        lease = self.hbm.weight_lease(name)
        if lease is None or not lease.pageable \
                or lease.state != hbm_mod.RESIDENT:
            return None
        freed = self.hbm.page_out(lease, reason="scale_to_zero")
        if not freed:
            return None
        return {"nbytes": lease.nbytes,
                "restore_estimate_s":
                    self.hbm.restore_estimate_s(lease.nbytes)}

    def restore_model(self, name: str) -> bool:
        """Restore a paged-out model's weights (chunked-parallel
        host->device) and re-admit traffic. May evict colder models;
        raises the allocator's honest retryable deferral when the
        budget loses the arbitration. False when the model is not
        paged out."""
        lease = self.hbm.weight_lease(name)
        if lease is None or lease.state != hbm_mod.PAGED_OUT:
            return False
        return self.hbm.restore(lease, reason="restore")

    def _kick_restore(self, name: str) -> Optional[float]:
        """Admission-miss hook for models paged out by *eviction*
        (the autoscaler only tracks its own scale-to-zero decisions):
        single-flight background restore + honest Retry-After from
        measured bandwidth. None when the model is not paged out."""
        lease = self.hbm.weight_lease(name)
        if lease is None or lease.state != hbm_mod.PAGED_OUT:
            return None
        estimate = self.hbm.restore_estimate_s(lease.nbytes)
        if self.hbm.claim_restore(lease):
            thread = threading.Thread(
                target=self._restore_in_background, args=(name,),
                name="hbm-restore-%s" % name, daemon=True)
            thread.start()
        return estimate

    def _restore_in_background(self, name: str) -> None:
        try:
            self.restore_model(name)
        except Exception:  # noqa: BLE001 — the deferral already told
            # the client when to retry; the claim was cleared by
            # restore()'s failure path, so the next arrival re-kicks.
            _LOG.warning("hbm: background restore of %s failed", name,
                        exc_info=True)

    def shutdown(self) -> None:
        """Teardown: flip /v2/health/ready to not-ready FIRST (load
        balancers stop routing while the drain completes), then stop
        batchers (which drain their queues) and flush buffered trace
        records — log_frequency>0 buffers would otherwise silently drop
        the tail of every trace file (Triton flushes on trace-file
        close)."""
        self.ready = False
        # The controller first: a resize racing the teardown below
        # would re-create queues the drain already stopped.
        self.autoscaler.stop()
        with self._sequencers_lock:
            sequencers, self._sequencers = dict(self._sequencers), {}
        for sequencer in sequencers.values():
            sequencer.stop()  # backlogged starts fail UNAVAILABLE
        with self._batchers_lock:
            batchers, self._batchers = dict(self._batchers), {}
        for batcher in batchers.values():
            batcher.stop()
        with self._replica_lock:
            replica_sets, self._replica_sets = dict(self._replica_sets), {}
        for replica_set in replica_sets.values():
            replica_set.stop()  # after batchers: they drain through it
        with self._trace_lock:
            for name, state in self._trace_state.items():
                if state["buffer"]:
                    self._flush_trace(
                        name, self._effective_trace_settings(name), state)
        # After the schedulers: a draining batcher's tail may still be
        # encoding direct-path responses through the shared fetcher.
        self.fetcher.shutdown()
        with self._stage_insert_lock:
            pool, self._stage_insert_pool = self._stage_insert_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # -- inference -------------------------------------------------------

    def _replicas_for(self, model):
        """Lazily creates the model's ReplicaSet (None when the model
        declares no instance group). The repository's registered
        factory instantiates the per-replica executables and re-
        initializes an ejected replica's weights during self-healing;
        the scope_fn threads this core's chaos scope into replica
        executions so scoped AND replica-targeted faults land inside
        the right fault domain."""
        from client_tpu.server.replicas import ReplicaSet, wants_replicas

        if not wants_replicas(model):
            return None
        with self._replica_lock:
            replica_set = self._replica_sets.get(model.name)
            if replica_set is None:
                replica_set = ReplicaSet(
                    model,
                    factory=self.repository.factory(model.name),
                    scope_fn=lambda: self.chaos_scope,
                    # Breaker trips / watchdog ejections stamp the
                    # flight-ring traces that led up to them.
                    event_hook=self.flight.mark_incident,
                )
                self._replica_sets[model.name] = replica_set
            return replica_set

    def _execution_target(self, model):
        """Where this model's executions run: the ReplicaSet's routing
        proxy for instance-group models, the model itself otherwise."""
        replica_set = self._replicas_for(model)
        return model if replica_set is None else replica_set.proxy

    def _batcher_for(self, model):
        """Lazily creates the model's dynamic batcher (None when the
        model doesn't opt in)."""
        from client_tpu.server.batcher import (
            DynamicBatcher,
            wants_dynamic_batching,
        )

        if not wants_dynamic_batching(model):
            return None
        from client_tpu.server.replicas import wants_replicas

        with self._batchers_lock:
            batcher = self._batchers.get(model.name)
            if batcher is None:
                stats = self._stats_for(model.name)
                devstats = self.devstats
                if wants_replicas(model):
                    # Replicated models record busy time and compile
                    # attribution inside each replica's own device
                    # queue (ReplicaSet._run_on) — routed per device,
                    # never double-counted through the batcher span.
                    stats_hook = stats.record_batch
                    compile_scope = None
                else:
                    def stats_hook(size, compute_ns, fetch_ns,
                                   _record=stats.record_batch,
                                   _dev=devstats):
                        _record(size, compute_ns, fetch_ns)
                        # The fused execution's compute span IS the
                        # device-side duration for the busy counter.
                        _dev.record_busy(None, compute_ns)
                    compile_scope = devstats.compile_scope
                batcher = DynamicBatcher(
                    model,
                    execution_target=self._execution_target(model),
                    compile_scope=compile_scope,
                    max_queue_delay_us=int(
                        getattr(model, "max_queue_delay_us", 500)),
                    preferred_batch_sizes=list(
                        getattr(model, "preferred_batch_sizes", []) or []),
                    delay_min_us=int(getattr(model, "delay_min_us", 0)),
                    delay_max_us=int(getattr(model, "delay_max_us", 0)),
                    pipeline_depth=int(
                        getattr(model, "pipeline_depth", 0)),
                    fetch_workers=int(
                        getattr(model, "fetch_pool_workers", 0)),
                    stats_hook=stats_hook,
                    max_queue_size=int(
                        getattr(model, "max_queue_size", 0)),
                    default_timeout_us=int(getattr(
                        model, "default_queue_policy_timeout_us", 0)),
                    allow_timeout_override=bool(
                        getattr(model, "allow_timeout_override", True)),
                    timeout_action=str(
                        getattr(model, "timeout_action", "REJECT")),
                    reject_hook=stats.record_rejected,
                    timeout_hook=stats.record_timeout,
                    priority_levels=int(
                        getattr(model, "priority_levels", 0)),
                    default_priority_level=int(
                        getattr(model, "default_priority_level", 0)),
                    priority_policies=dict(
                        getattr(model, "priority_queue_policies", {})
                        or {}),
                    shed_watermark=float(
                        getattr(model, "shed_watermark", 0.0)),
                    shed_hook=stats.record_shed,
                    wasted_hook=stats.record_wasted_ns,
                    telemetry=self.telemetry,
                    overlapped_fetch=bool(
                        getattr(model, "overlapped_fetch", True)),
                    fetch_chunk_bytes=int(
                        getattr(model, "fetch_chunk_bytes", 0)),
                )
                self._batchers[model.name] = batcher
            return batcher

    def _sequencer_for(self, model):
        """Lazily creates the model's sequence scheduler (None when the
        model doesn't declare sequence_batching)."""
        from client_tpu.server.sequence import (
            SequenceScheduler,
            wants_sequence_batching,
        )

        if not wants_sequence_batching(model):
            return None
        with self._sequencers_lock:
            sequencer = self._sequencers.get(model.name)
            if sequencer is None:
                stats = self._stats_for(model.name)
                sequencer = SequenceScheduler(
                    model,
                    # Oldest-strategy steps dispatch through the
                    # model's own dynamic batcher so concurrent
                    # sequences fuse (None for direct-only models).
                    batcher=self._batcher_for(model),
                    execution_target=self._execution_target(model),
                    reject_hook=stats.record_rejected,
                    timeout_hook=stats.record_timeout,
                )
                self._sequencers[model.name] = sequencer
            return sequencer

    def _record_composing(self, name: str, count: int,
                          compute_ns: int, executions: int = 1,
                          queue_ns: int = 0) -> None:
        """Stats hook ensembles call per composing-step execution, so
        composing models' per-window deltas are real (Triton records
        composing executions through their own schedulers). Batched
        steps pass executions=0 for non-leader riders and their
        scheduler queue time as ``queue_ns`` — composing rows keep the
        same queue/compute split as top-level requests."""
        self._stats_for(name).record(count, queue_ns, 0, compute_ns, 0,
                                     ok=True, executions=executions)

    # -- ensemble dataflow ------------------------------------------------

    def _ensemble_dataflow(self, model, inputs, params, trace,
                           queue_from_ns: int, cancel=None):
        """Device-resident execution of an ensemble's step graph (the
        ``device_dataflow=True`` serving path): builds the per-request
        DataflowContext — per-stage batchers, replica-routed targets,
        composing stats, telemetry, and the stage-output cache
        closures — and runs :meth:`EnsembleModel.infer_dataflow`.
        Returns ``(outputs, queue_ns_total)``; outputs may still be
        device arrays (``_fetch_outputs`` lands them at the edge)."""
        from client_tpu.models.ensemble import DataflowContext

        cache_lookup = cache_insert = None
        if self.response_cache.enabled:
            digest = self._ensemble_edge_digest(model, inputs, params)
            if digest is not None:
                cache_lookup, cache_insert = \
                    self._stage_cache_closures(model, digest)
        ctx = DataflowContext(
            trace=trace,
            telemetry=(self.telemetry if self.telemetry.enabled
                       else None),
            stats_recorder=self._record_composing,
            batcher_for=self._batcher_for,
            target_for=self._execution_target,
            cache_lookup=cache_lookup,
            cache_insert=cache_insert,
            queue_from_ns=queue_from_ns,
            cancel=cancel,
            arena=getattr(self.memory, "arena", None),
        )
        return model.infer_dataflow(inputs, params, ctx)

    @staticmethod
    def _ensemble_edge_digest(model, inputs, params) -> Optional[bytes]:
        """Content hash of an ensemble request at the graph edge
        (decoded host inputs + cache-relevant params) — the base every
        stage-cache key derives from. ``None`` = uncacheable (object-
        dtype input, or anything that will not hash stably)."""
        h = hashlib.blake2b(digest_size=16)
        h.update(model.name.encode())
        try:
            for name in sorted(inputs):
                array = np.asarray(inputs[name])
                if array.dtype.hasobject:
                    return None
                h.update(b"\x01")
                h.update(name.encode())
                h.update(array.dtype.str.encode())
                h.update(repr(array.shape).encode())
                h.update(array.tobytes())
            for key in sorted(params):
                if key in cache_mod._UNCACHED_PARAMS:
                    continue
                h.update(b"\x02")
                h.update(key.encode())
                h.update(repr(params[key]).encode())
        except Exception:  # noqa: BLE001 — uncacheable, never fatal
            return None
        return h.digest()

    def _stage_cache_closures(self, ensemble, digest: bytes):
        """(cache_lookup, cache_insert) bound to one request's edge
        digest. Stage keys chain the prefix model names, so two
        ensembles sharing a backbone but differing upstream never
        collide; entries are attributed to the STEP's model name, so
        the existing unload listener invalidates them with the model
        that produced them."""
        steps = ensemble._steps

        def stage_key(k: int) -> bytes:
            h = hashlib.blake2b(digest_size=16)
            h.update(b"ens-stage")
            h.update(digest)
            h.update(k.to_bytes(4, "little"))
            for name, _, _ in steps[:k + 1]:
                h.update(b"\x00")
                h.update(name.encode())
            return h.digest()

        def cache_lookup(k: int, step_model):
            if not cache_mod.wants_response_cache(step_model):
                return None
            data = self.response_cache.lookup(stage_key(k))
            if data is None:
                return None
            decoded = cache_mod.decode_tensors(data)
            if decoded is None:
                return None
            # The composing model's own hit counter (PR-1 fields) plus
            # the ensemble-level short-circuit counter: the hit made
            # the whole prefix subgraph free.
            self._stats_for(step_model.name).record_cache_hit(0)
            if self.telemetry.enabled:
                self.telemetry.record_ensemble_cache_hit(ensemble.name)
            return decoded

        def cache_insert(k: int, step_model, outputs):
            if not cache_mod.wants_response_cache(step_model):
                return
            key = stage_key(k)
            if self.response_cache.lookup(key) is not None:
                return  # hot-set steady state: already cached
            self._stage_insert_async(step_model.name, key, outputs)

        return cache_lookup, cache_insert

    def _stage_insert_async(self, model_name: str, key: bytes,
                            outputs) -> None:
        pool = self._stage_insert_pool
        if pool is None:
            with self._stage_insert_lock:
                pool = self._stage_insert_pool
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="stage-cache")
                    self._stage_insert_pool = pool

        def work():
            try:
                data = cache_mod.encode_tensors(outputs)
                if data is not None:
                    self.response_cache.insert_bytes(model_name, key,
                                                     data)
            except Exception:  # noqa: BLE001 — caching is best-effort
                pass

        try:
            pool.submit(work)
        except RuntimeError:
            pass  # shutting down

    def _tenant_of(self, request: pb.ModelInferRequest) -> Optional[str]:
        """Tenant identity for quota/accounting purposes, or None when
        nothing needs it (no quotas configured AND the request is
        untagged — the zero-cost common case)."""
        param = request.parameters.get("tenant")
        tagged = param is not None and param.string_param
        if not tagged and (self.tenant_quotas is None
                           or not self.tenant_quotas.enabled):
            return None
        from client_tpu.server.qos import ANONYMOUS_TENANT

        return str(param.string_param) if tagged else ANONYMOUS_TENANT

    def _flight_admission_reject(self, request: pb.ModelInferRequest,
                                 trace_context: Optional[str],
                                 error: InferenceServerException
                                 ) -> None:
        """Admission-stage failures (tenant-quota 429, drain/unknown-
        model rejects) fire BEFORE the scratch-capture path in
        _infer_admitted, so they would never reach the flight ring —
        retain them here with a root-only trace so the forensic layer
        covers every drop, not just post-admission ones. Never raises:
        callers are about to re-raise the REAL error, and forensics
        must not replace it."""
        try:
            flight = self.flight
            if not flight.enabled:
                return
            # Clamped here too: these strings land in the trace ROOT
            # attrs (serialized into the record's span tree), which
            # observe()'s top-level field clamps do not cover.
            model_name = str(request.model_name)[
                :flightrec.MAX_NAME_CHARS]
            request_id = str(request.id)[:flightrec.MAX_ID_CHARS]
            trace = spantrace.RequestTrace(
                trace_context,
                attrs={"model": model_name, "request_id": request_id},
                sampled=False)
            trace.finish(error=str(error))
            flight.observe(None, model_name, request_id, trace,
                           error=str(error), status=error.status())
        except Exception:  # noqa: BLE001 — forensics never affect
            pass  # serving

    def infer(self, request: pb.ModelInferRequest,
              trace_context: Optional[str] = None,
              cancel: Optional[cancel_mod.CancelToken] = None
              ) -> pb.ModelInferResponse:
        # Request-id correlation happens at the transport front-ends
        # (mint_request_id): they own their per-call protos, whereas a
        # direct core caller may legitimately share one request object
        # across threads (the bench's closed loops do) and an in-place
        # mint would race.
        # Cancellation: transports pass the token they wired to their
        # disconnect signal; direct callers get one minted here so
        # wire cancellation by request id works everywhere.
        cancel = self._cancel_begin(request, cancel)
        try:
            # Tenant quota admission runs FIRST — before the model is
            # acquired — so an over-quota tenant cannot even hold an
            # in-flight slot during a drain.
            with _TenantAdmission(self, request,
                                  trace_context) as admission:
                # acquire = READY check + in-flight increment in one
                # atomic step: a graceful unload drains exactly the
                # requests admitted before it flipped the state
                # (repository.begin_unload).
                try:
                    model = self.repository.acquire(request.model_name,
                                                    request.model_version)
                except InferenceServerException as e:
                    # Transparent cold start: a model the autoscale
                    # controller scaled to zero is not "unknown" — the
                    # first arrival kicks exactly one background reload
                    # and is told honestly how long warming will take.
                    retry = self.autoscaler.on_admission_miss(
                        request.model_name)
                    if retry is None:
                        # Paged out by HBM eviction rather than by the
                        # autoscaler: same transparency, restore instead
                        # of reload, Retry-After from measured restore
                        # bandwidth.
                        retry = self._kick_restore(request.model_name)
                    if retry is not None:
                        e = status_map.retryable_error(
                            "model '%s' is cold-starting (weights are "
                            "paged out or it was scaled to zero); "
                            "warming now"
                            % request.model_name, retry_after_s=retry)
                    self._flight_admission_reject(request, trace_context,
                                                  e)
                    raise e
                admission.model_name = model.name
                if cancel is not None and cancel.deadline_ns is None:
                    # The token carries the SAME deadline the PR-2
                    # queue policy enforces pre-dispatch — past
                    # dispatch, stage-boundary checks keep enforcing it
                    # (DELAY models have an advisory deadline: none).
                    cancel.deadline_ns = self._queue_deadline_ns(
                        model, request)
                # Admission is the eviction policy's heat signal: stamp
                # every lease of this model hot (lock-only, never
                # raises).
                self.hbm.touch_model(model.name)
                try:
                    response = self._infer_admitted(model, request,
                                                    trace_context,
                                                    cancel=cancel)
                    admission.ok = True
                    return response
                except InferenceServerException as e:
                    # Stamped error log: the line joins a client-side
                    # failure to its trace/statistics by request id.
                    _LOG.debug("request %s for model '%s' failed: %s",
                               request.id, model.name, e)
                    stage = getattr(e, "cancel_stage", None)
                    if stage is not None:
                        self._stats_for(model.name).record_cancelled(
                            stage)
                    raise
                finally:
                    self.repository.release(model.name)
        finally:
            if cancel is not None:
                self.cancel.untrack(cancel)

    def _cancel_begin(self, request: pb.ModelInferRequest,
                      cancel: Optional[cancel_mod.CancelToken]
                      ) -> Optional[cancel_mod.CancelToken]:
        """Mint-or-adopt the request's CancelToken at admission and
        index it by request id so explicit wire cancels can find it.
        Returns None when the subsystem is off AND no transport token
        was supplied — every stage check downstream short-circuits on
        `cancel is None`, which is the whole cost of the off arm."""
        registry = self.cancel
        if cancel is None:
            if not registry.enabled:
                return None
            cancel = registry.mint(request.id)
        elif not cancel.request_id and request.id:
            cancel.request_id = request.id
        registry.track(cancel)
        return cancel

    @staticmethod
    def _queue_deadline_ns(model: ServedModel,
                           request: pb.ModelInferRequest
                           ) -> Optional[int]:
        """Absolute deadline under PR-2 queue-policy semantics: the
        per-request `timeout` parameter when the model allows the
        override, else the model's default_queue_policy_timeout_us;
        None for DELAY models (advisory) and deadline-less requests."""
        if str(getattr(model, "timeout_action", "REJECT")).upper() \
                != "REJECT":
            return None
        timeout_us = 0
        if getattr(model, "allow_timeout_override", True) \
                and "timeout" in request.parameters:
            try:
                timeout_us = int(
                    _param_value(request.parameters["timeout"]) or 0)
            except (TypeError, ValueError):
                timeout_us = 0
        if timeout_us <= 0:
            timeout_us = int(getattr(
                model, "default_queue_policy_timeout_us", 0))
        return cancel_mod.deadline_from_timeout_us(timeout_us)

    def cancel_request(self, request_id: str,
                       reason: str = cancel_mod.REASON_WIRE_CANCEL
                       ) -> bool:
        """Explicit wire cancellation by request id (the HTTP
        `POST /v2/cancel/<id>` route and hedge-loser cancels). True if
        an in-flight request was found and signalled."""
        return self.cancel.cancel(request_id, reason)

    def _infer_admitted(self, model: ServedModel,
                        request: pb.ModelInferRequest,
                        trace_context: Optional[str] = None,
                        cancel: Optional[cancel_mod.CancelToken] = None
                        ) -> pb.ModelInferResponse:
        if getattr(model, "stats_recorder", False) is None:
            model.stats_recorder = self._record_composing
        if getattr(model, "batcher_resolver", False) is None:
            # Composing steps route through each model's OWN dynamic
            # batcher (Triton semantics: an ensemble step enters the
            # composing model's scheduler), so concurrent ensemble
            # requests fuse their backbone executions.
            model.batcher_resolver = self._batcher_for
        stats = self._stats_for(model.name)
        trace = self._trace_begin(model.name, trace_context, request.id)
        flight = self.flight
        ftrace = trace
        if ftrace is None and (flight.enabled
                               or self.devstats.profiler.armed):
            # Tail sampling (flight recorder): the span tree is
            # captured for EVERY request into a scratch trace; whether
            # it survives is decided RETROACTIVELY at completion
            # (error/shed/timeout/slow), when the request's fate is
            # known — never by a dice roll at start. Unkept scratches
            # are discarded without ever being rendered. An armed
            # profiler window forces capture too (even with the flight
            # recorder off) so the span-derived chrome trace always
            # has material.
            ftrace = spantrace.RequestTrace(
                trace_context,
                attrs={"model": model.name, "request_id": request.id},
                sampled=False)
        if ftrace is None:
            return self._infer_routed(model, request, stats, None,
                                      cancel=cancel)
        error: Optional[str] = None
        status: Optional[str] = None
        token = (flight.track(model.name, request.id, ftrace)
                 if flight.enabled else None)
        try:
            return self._infer_routed(model, request, stats, ftrace,
                                      cancel=cancel)
        except InferenceServerException as e:
            error = str(e)
            status = e.status()
            raise
        except Exception as e:
            error, status = str(e), "INTERNAL"
            raise
        finally:
            if cancel is not None and cancel.stage is not None:
                # Terminal span attr: where the cancel signal landed
                # (traces + flight ring show the abandoned stage).
                ftrace.root.attrs["cancelled"] = cancel.stage
            ftrace.finish(error=error)
            if trace is not None:
                self._trace_emit(model.name, request.id, trace)
            try:
                flight.observe(model, model.name, request.id, ftrace,
                               error=error, status=status, token=token)
            except Exception:  # noqa: BLE001 — a recorder fault must
                pass  # never mask the request's own outcome
            profiler = self.devstats.profiler
            if profiler.armed:
                profiler.tap(model.name, request.id, ftrace)

    def _infer_routed(self, model: ServedModel,
                      request: pb.ModelInferRequest, stats: _ModelStats,
                      trace: Optional[spantrace.RequestTrace],
                      cancel: Optional[cancel_mod.CancelToken] = None
                      ) -> pb.ModelInferResponse:
        """Cache-aware routing for one admitted request: lookup /
        single-flight when the model opted into the response cache,
        else straight to execution."""
        cache = self.response_cache
        if not (cache.enabled and wants_response_cache(model)):
            return self._infer_executed(
                model, request, stats, trace,
                t0_ns=trace.root.start_ns if trace is not None else None,
                cancel=cancel)
        # Cache lookup runs on the WIRE request, before any input
        # decoding: a hit skips deserialization, queue/batcher, model
        # execution, and output encoding — it pays only the content
        # hash, one dict probe, and a proto copy. Sequence requests
        # and shared-memory I/O yield key=None (bypass).
        key = request_cache_key(model.name, model.version, request)
        if key is None:
            if trace is not None:
                mark = time.monotonic_ns()
                trace.add_timed(spantrace.SPAN_CACHE_LOOKUP,
                                trace.root.start_ns, mark,
                                {"outcome": "bypass"})
                return self._infer_executed(model, request, stats, trace,
                                            t0_ns=mark, cancel=cancel)
            return self._infer_executed(model, request, stats, trace,
                                        cancel=cancel)
        # Priority is coerced BEFORE the cache probe on QoS models so
        # (a) an out-of-range value fails INVALID_ARGUMENT even when
        # the answer is cached — caching must not change validation
        # semantics — and (b) a new flight carries its leader's class.
        req_priority = 0
        levels = int(getattr(model, "priority_levels", 0))
        if levels > 0:
            from client_tpu.server.qos import coerce_priority

            value = (_param_value(request.parameters["priority"])
                     if "priority" in request.parameters else None)
            req_priority = coerce_priority(
                value, levels,
                int(getattr(model, "default_priority_level", 0)))
        t_cache = time.monotonic_ns()
        # Single-flight: the first miss for a key leads and executes;
        # concurrent identical misses follow — they are served the
        # leader's response instead of executing N copies of the same
        # work. A burst of N identical requests runs the model once.
        # The probe is one atomic step (entry, live flight, or new
        # leadership) so a leader resolving between a lookup and a
        # begin cannot hand a late thread a redundant execution.
        cached, flight, leader = cache.lookup_or_begin(key, req_priority)
        if cached is not None:
            response = self._finish_cache_hit(model, request, stats,
                                              cached, t_cache,
                                              priority=req_priority)
            if trace is not None:
                # The lookup span covers probe AND serve (parse +
                # id stamp) so a hit's trace tiles from root start.
                trace.add_timed(spantrace.SPAN_CACHE_LOOKUP,
                                trace.root.start_ns,
                                time.monotonic_ns(), {"outcome": "hit"})
            return response
        # A strictly higher class must not coalesce behind a
        # lower-class leader: the follower would inherit the leader's
        # position at the back of the lowest-priority queue — exactly
        # the saturation condition where priority dispatch is supposed
        # to let it overtake. It executes independently instead (the
        # priority queues fuse it into the next execution); the leader
        # keeps flight ownership, insert, and follower wake-up.
        overtake = (not leader and flight is not None and req_priority
                    and flight.priority and req_priority < flight.priority)
        mark = 0
        if trace is not None:
            mark = time.monotonic_ns()
            outcome = ("miss" if leader
                       else "priority_bypass" if overtake else "follower")
            trace.add_timed(spantrace.SPAN_CACHE_LOOKUP,
                            trace.root.start_ns, mark,
                            {"outcome": outcome})
        if overtake:
            return self._infer_executed(
                model, request, stats, trace,
                t0_ns=mark if trace is not None else None,
                cancel=cancel)
        if not leader:
            try:
                response = self._await_flight(model, request, stats, cache,
                                              flight, t_cache,
                                              priority=req_priority,
                                              cancel=cancel)
            except Exception:
                if trace is not None:
                    trace.add_timed(spantrace.SPAN_CACHE_WAIT, mark,
                                    time.monotonic_ns(),
                                    {"outcome": "timeout"})
                raise
            if trace is not None:
                end_ns = time.monotonic_ns()
                trace.add_timed(spantrace.SPAN_CACHE_WAIT, mark, end_ns,
                                {"outcome": ("served" if response is not None
                                             else "leader_failed")})
                mark = end_ns
            if response is not None:
                return response
            # Leader failed: fall back to an independent execution so
            # one fault never fans out across the coalesced burst.
            flight = None
        try:
            response = self._infer_executed(
                model, request, stats, trace,
                t0_ns=mark if trace is not None else None,
                cancel=cancel)
        except Exception:
            # A cancelled leader aborts and fails its flight — exactly
            # right for an all-cancelled burst; a follower that was NOT
            # cancelled falls back to an independent execution below,
            # so one abandoned leader never takes live followers down.
            if flight is not None:
                cache.fail_flight(key, flight)
            raise
        insert_start = (trace.timeline[-1] if trace is not None
                        and trace.timeline else 0)
        try:
            # Success only: failed executions are never inserted.
            cache.insert(model.name, key, response)
            stats.record_cache_miss(time.monotonic_ns() - t_cache)
        finally:
            # Followers are woken no matter what — a failed insert
            # must never strand the coalesced burst.
            if flight is not None:
                cache.resolve_flight(key, flight, response)
        if trace is not None and insert_start:
            trace.add_timed(spantrace.SPAN_CACHE_INSERT, insert_start,
                            time.monotonic_ns())
        return response

    def _finish_cache_hit(self, model: ServedModel,
                          request: pb.ModelInferRequest, stats: _ModelStats,
                          cached: bytes, t_cache: int, priority: int = 0
                          ) -> pb.ModelInferResponse:
        """Serves a stored response: parse the cached bytes, stamp the
        requester's id, count an inference (never an execution), keep
        queue/compute sections untouched (hits bypass them — the perf
        caveat). ``priority`` labels the success in priority_stats —
        a hit served to a QoS class still counts toward that class's
        goodput."""
        response = pb.ModelInferResponse()
        response.ParseFromString(cached)
        response.id = request.id
        ns = time.monotonic_ns() - t_cache
        stats.record_cache_hit(ns)
        stats.record(self._batch_size(model, request), 0, 0, 0, 0,
                     ok=True, executions=0, total_ns=ns,
                     priority=priority)
        # Hits land in the request-duration histogram too (they are
        # served requests an SLO covers) but skip the stage families —
        # a hit never queues, executes, or fetches.
        self.telemetry.observe_request(model.name, ns / 1000.0)
        return response

    def _await_flight(self, model: ServedModel,
                      request: pb.ModelInferRequest, stats: _ModelStats,
                      cache: ResponseCache, flight, t_cache: int,
                      priority: int = 0,
                      cancel: Optional[cancel_mod.CancelToken] = None
                      ) -> Optional[pb.ModelInferResponse]:
        """Follower side of single-flight: wait for the leader's
        response, bounded by this request's own queue deadline (PR-2
        semantics: per-request `timeout` when the model allows the
        override, else default_queue_policy_timeout_us; 0 = wait for
        the leader — whose own execution is bounded). A model whose
        timeout_action is DELAY keeps its deadline advisory here too:
        the follower waits the leader out instead of hard-failing.
        A cancelled follower DETACHES without touching the leader's
        flight (chunked wait below): the leader and remaining
        followers are unaffected, and an all-cancelled burst dies when
        the cancelled leader aborts on its own stage checks. Returns
        None when the leader failed (caller executes independently)."""
        timeout_us = 0
        if getattr(model, "allow_timeout_override", True) \
                and "timeout" in request.parameters:
            try:
                # Same coercion as the batcher's _timeout_ns_for: HTTP
                # clients send `timeout` as a string/double parameter.
                timeout_us = int(
                    _param_value(request.parameters["timeout"]) or 0)
            except (TypeError, ValueError):
                timeout_us = 0
        if timeout_us <= 0:
            timeout_us = int(getattr(
                model, "default_queue_policy_timeout_us", 0))
        if str(getattr(model, "timeout_action", "REJECT")).upper() \
                != "REJECT":
            timeout_us = 0  # DELAY: deadline is advisory, never fatal
        if cancel is None:
            served = flight.event.wait(
                timeout_us / 1e6 if timeout_us > 0 else None)
        else:
            # The flight event cannot be set on cancel (it would wake
            # every follower), so a cancellable follower polls it in
            # short chunks — detach latency is bounded by the chunk.
            wait_deadline = (time.monotonic_ns() + timeout_us * 1000
                             if timeout_us > 0 else None)
            served = flight.event.is_set()
            while not served:
                if cancel.cancelled():
                    stats.record(1, 0, 0, 0,
                                 time.monotonic_ns() - t_cache, ok=False)
                    cancel.raise_if_cancelled("queue")
                remaining = (None if wait_deadline is None else
                             (wait_deadline - time.monotonic_ns()) / 1e9)
                if remaining is not None and remaining <= 0:
                    break
                served = flight.event.wait(
                    0.05 if remaining is None else min(0.05, remaining))
        if not served:
            stats.record_timeout(priority)
            stats.record(1, 0, 0, 0,
                         time.monotonic_ns() - t_cache, ok=False)
            raise InferenceServerException(
                "request %s for model '%s' expired after %d us waiting "
                "on an identical in-flight request (single-flight)"
                % (request.id, model.name, timeout_us),
                status="DEADLINE_EXCEEDED")
        if flight.failed or flight.response is None:
            return None
        cache.record_coalesced(model.name)
        response = pb.ModelInferResponse()
        response.CopyFrom(flight.response)
        response.id = request.id
        ns = time.monotonic_ns() - t_cache
        stats.record_cache_hit(ns)
        stats.record(self._batch_size(model, request), 0, 0, 0, 0,
                     ok=True, executions=0, total_ns=ns,
                     priority=priority)
        self.telemetry.observe_request(model.name, ns / 1000.0)
        return response

    def _infer_executed(self, model: ServedModel,
                        request: pb.ModelInferRequest,
                        stats: _ModelStats,
                        trace: Optional[spantrace.RequestTrace] = None,
                        t0_ns: Optional[int] = None,
                        cancel: Optional[cancel_mod.CancelToken] = None
                        ) -> pb.ModelInferResponse:
        # Traced requests chain t0 off the caller's last span boundary
        # (root start / cache-lookup end) so the admission slice lands
        # in the decode span instead of an untracked gap; untraced
        # requests keep a fresh read.
        t0 = t0_ns if t0_ns is not None else time.monotonic_ns()
        queue_ns = 0
        executions = 1
        priority = 0
        direct_busy = False
        dataflow = False
        try:
            chaos.inject(model.name, scope=self.chaos_scope,
                         cancel=cancel)
            # fault injection (no-op unless configured); drops/errors
            # ride the normal failure path
            inputs, params = self._decode_inputs(model, request)
            if cancel is not None and cancel.cancelled():
                # Signal landed during decode/admission: nothing is
                # queued yet, drop before touching any scheduler.
                cancel.raise_if_cancelled("queue")
            if getattr(model, "priority_levels", 0) > 0:
                # Same coercion/validation the batcher applies — done
                # here too so the success stats can be labeled per
                # class and an out-of-range priority fails before any
                # queueing (INVALID_ARGUMENT, never a silent drop).
                from client_tpu.server.qos import coerce_priority

                priority = coerce_priority(
                    params.get("priority"), model.priority_levels,
                    int(getattr(model, "default_priority_level", 0)))
            t1 = time.monotonic_ns()
            if trace is not None:
                # Spans tile the t0..t3 timeline exactly (decode =
                # t0->t1, execute = t1->t2 around the scheduler spans,
                # encode = t2->t3) so the stage-attribution table can
                # account for ~all of the server time even on
                # microsecond-scale models where inter-stage framework
                # gaps would otherwise dominate.
                trace.add_timed(spantrace.SPAN_DECODE, t0, t1,
                                {"inputs": len(inputs)})
            batcher = self._batcher_for(model)
            sequencer = (self._sequencer_for(model)
                         if params.get("sequence_id") else None)
            if sequencer is not None:
                # Correlated request: the sequence scheduler owns slot
                # assignment, per-sequence ordering, control/state
                # injection, and (oldest strategy) dispatch into the
                # dynamic batcher for cross-sequence step fusion.
                batch = self._batch_size(model, request)
                outputs, queue_ns, executions = sequencer.infer(
                    inputs, params, batch, trace=trace, cancel=cancel)
            elif getattr(model, "device_dataflow", False) \
                    and hasattr(model, "infer_dataflow") \
                    and "sequence_id" not in params:
                # Device-resident ensemble dataflow: the core executes
                # the step graph itself — per-stage batching (fusing
                # with concurrent ensembles AND standalone traffic),
                # per-stage replica routing, composing-cache short-
                # circuits. Takes precedence over the ensemble's OWN
                # batcher: gathering whole ensembles would serialize
                # the stage pipeline behind one leader thread, while
                # per-stage fusion reaches the same padded XLA calls
                # without it.
                dataflow = True
                outputs, queue_ns = self._ensemble_dataflow(
                    model, inputs, params, trace,
                    t1 if trace is not None else 0, cancel=cancel)
            elif batcher is not None and "sequence_id" not in params:
                batch = self._batch_size(model, request)
                outputs, queue_ns, leader = batcher.infer(
                    inputs, params, batch, trace=trace,
                    queue_from_ns=t1 if trace is not None else 0,
                    priority=priority if priority else None,
                    # Per-member early completion: the batcher wakes
                    # this call as soon as the outputs THIS request
                    # asked for have landed ([] = wants everything).
                    wanted_outputs=[t.name for t in request.outputs]
                    or None,
                    cancel=cancel)
                # Fused requests share one model execution; only its
                # leader bumps execution_count (Triton semantics).
                executions = 1 if leader else 0
            else:
                # Direct path: instance-group models route through the
                # ReplicaSet proxy (health-routed dispatch + bounded
                # re-dispatch; busy time and compile attribution land
                # inside the replica's own device queue); everything
                # else executes in place under a compile-attribution
                # scope, and its device_execute duration feeds the
                # busy-time counter below.
                replica_set = self._replicas_for(model)
                if replica_set is not None:
                    outputs = replica_set.proxy.infer(inputs, params)
                elif self.devstats.enabled:
                    with self.devstats.compile_scope(
                            model.name,
                            devstats_mod.shape_fingerprint(inputs)):
                        outputs = model.infer(inputs, params)
                    direct_busy = True
                else:  # A/B off arm: zero devstats cost on the path
                    outputs = model.infer(inputs, params)
            t2 = time.monotonic_ns()
            if direct_busy:
                self.devstats.record_busy(None, t2 - t1)
            if cancel is not None and cancel.cancelled_or_expired(t2):
                # Deadline/cancel landed during (or right after)
                # execution: the compute already happened — account it
                # as wasted — but fetch and encode are still saved.
                stats.record_wasted_ns((t2 - t1) - queue_ns)
                cancel.raise_if_cancelled("execute", t2)
            # Span boundaries are CHAINED off single clock reads
            # (decode ends exactly where execute starts, etc.): two
            # separate reads around a boundary would let a GIL
            # deschedule land between them as untracked time, and at
            # concurrency those slices dominate microsecond models.
            span_mark = t2
            if trace is not None and sequencer is None \
                    and batcher is None and not dataflow:
                # device_execute = end of decode to model return
                # (async-dispatch models return lazy arrays; the
                # forced materialization lands in relay_fetch below).
                trace.add_timed(spantrace.SPAN_DEVICE_EXECUTE, t1, t2)
            # Direct/sequence-path responses materialize their
            # wire-bound outputs through the shared overlapped fetcher
            # BEFORE encode — all device->host copies issued at once,
            # landing-order processing, relay_fetch spans per output
            # (the device->host tax ROADMAP item 1 names, measured per
            # output instead of estimated). Batcher-path outputs are
            # already host slices and pass through untouched.
            outputs, span_mark = self._fetch_outputs(
                model, request, outputs, trace, t2)
            response = self._encode_response(model, request, outputs)
            t3 = time.monotonic_ns()
            if trace is not None:
                trace.add_timed(spantrace.SPAN_ENCODE, span_mark, t3)
        except InferenceServerException:
            stats.record(1, 0, 0, 0, time.monotonic_ns() - t0, ok=False)
            raise
        except Exception as e:
            stats.record(1, 0, 0, 0, time.monotonic_ns() - t0, ok=False)
            raise InferenceServerException(
                "inference failed for model '%s' (request %s): %s"
                % (model.name, request.id, e),
                status="INTERNAL",
            )
        batch = self._batch_size(model, request)
        stats.record(batch, queue_ns, t1 - t0, (t2 - t1) - queue_ns,
                     t3 - t2, ok=True, executions=executions,
                     priority=priority)
        telemetry = self.telemetry
        if telemetry.enabled:
            # Always-on SLO histograms: the end-to-end duration plus
            # the per-request stages that tile it (decode/queue/
            # execute/encode — the span-tree timeline, observed for
            # EVERY request, not just trace samples). SAMPLED requests
            # stamp their trace id as an OpenMetrics exemplar so a
            # hot-bucket outlier joins its span tree; flight scratch
            # traces never do (they are usually discarded).
            trace_id = spantrace.exemplar_id(trace)
            telemetry.observe_request(model.name, (t3 - t0) / 1000.0,
                                      trace_id)
            telemetry.observe_stage(model.name, "decode",
                                    (t1 - t0) / 1000.0, trace_id)
            if queue_ns:
                telemetry.observe_stage(model.name, "queue",
                                        queue_ns / 1000.0, trace_id)
            telemetry.observe_stage(model.name, "execute",
                                    ((t2 - t1) - queue_ns) / 1000.0,
                                    trace_id)
            telemetry.observe_stage(model.name, "encode",
                                    (t3 - t2) / 1000.0, trace_id)
        if trace is not None:
            trace.timeline = (t0, t1, t1 + queue_ns, t2, t3)
        return response

    def _fetch_outputs(self, model: ServedModel,
                       request: pb.ModelInferRequest, outputs,
                       trace: Optional[spantrace.RequestTrace],
                       mark_ns: int):
        """Device->host relay fetch for the wire-bound outputs of a
        direct/sequence-path response, through the shared overlapped
        fetcher (client_tpu.server.fetch): every copy is issued at
        once and processed in landing order, so the stage's wall clock
        is the slowest transfer instead of the sum. Outputs destined
        for a shared-memory region keep the zero-copy device-resident
        path — never forced to host; already-host outputs (the batcher
        path) pass through untouched. Traced requests span each
        landing under relay_fetch; the per-request fetch wall lands in
        the relay_fetch stage histogram. ``overlapped_fetch=False``
        restores the legacy behavior exactly (serial np.asarray for
        sampled requests, encode-time materialization otherwise — the
        bench A/B baseline arm). ``mark_ns`` is the chained span
        boundary; returns (outputs, new boundary)."""
        shm_outputs = {
            t.name for t in request.outputs
            if "shared_memory_region" in t.parameters
        }
        # Only the outputs the request will encode are fetched: a
        # subset request against a multi-output model must not pay
        # device->host traffic for tensors it never asked for (empty
        # request.outputs = everything, KServe semantics).
        requested = {t.name for t in request.outputs}
        device = {
            name: value for name, value in outputs.items()
            if name not in shm_outputs and relay.is_device_value(value)
            and (not requested or name in requested)
        }
        if not device:
            return outputs, mark_ns
        fetched = dict(outputs)
        if not bool(getattr(model, "overlapped_fetch", True)):
            if trace is None:
                return outputs, mark_ns  # encode materializes serially
            for name, value in device.items():
                host = np.asarray(value)
                end_ns = time.monotonic_ns()
                trace.add_timed(
                    spantrace.SPAN_RELAY_FETCH, mark_ns, end_ns,
                    {"output": name, "nbytes": int(host.nbytes)})
                mark_ns = end_ns
                fetched[name] = host
            return fetched, mark_ns
        fetch_start = mark_ns
        inflight = self.fetcher.start(
            device,
            chunk_bytes=int(getattr(model, "fetch_chunk_bytes", 0)))
        for handle in inflight.as_completed():
            end_ns = time.monotonic_ns()
            if handle.error is not None:
                error = handle.error
                if not isinstance(error, InferenceServerException):
                    error = InferenceServerException(
                        "output fetch failed for '%s': %s"
                        % (handle.name, error), status="INTERNAL")
                raise error
            fetched[handle.name] = handle.value
            if trace is not None:
                attrs = {"output": handle.name,
                         "nbytes": int(handle.value.nbytes),
                         "mode": "overlap"}
                if handle.chunks:
                    attrs["chunks"] = handle.chunks
                trace.add_timed(spantrace.SPAN_RELAY_FETCH, mark_ns,
                                end_ns, attrs)
            mark_ns = end_ns
        if self.telemetry.enabled:
            # Per-request fetch wall on the overlapped path (the
            # legacy arm's direct-path fetch happens inside encode and
            # is not separately observable).
            self.telemetry.observe_stage(
                model.name, "relay_fetch",
                (mark_ns - fetch_start) / 1000.0,
                spantrace.exemplar_id(trace))
        return fetched, mark_ns

    def stream_infer(
        self, request: pb.ModelInferRequest,
        trace_context: Optional[str] = None,
        cancel: Optional[cancel_mod.CancelToken] = None,
    ) -> Iterator[pb.ModelStreamInferResponse]:
        """Decoupled execution: yields one ModelStreamInferResponse per
        model response; the final response carries the
        triton_final_response=true parameter (empty if the model
        yielded nothing after its last data response and the client
        asked for empty finals)."""
        try:
            model = self.repository.get(request.model_name,
                                        request.model_version)
        except InferenceServerException as e:
            # Unknown-model/bad-version stream rejects are retained
            # like the unary path's — the forensic layer covers every
            # drop, streaming included.
            self._flight_admission_reject(request, trace_context, e)
            raise
        stats = self._stats_for(model.name)
        want_empty_final = (
            "triton_enable_empty_final_response" in request.parameters
            and request.parameters[
                "triton_enable_empty_final_response"
            ].bool_param
        )
        t0 = time.monotonic_ns()
        if not model.decoupled:
            response = self.infer(request, trace_context, cancel=cancel)
            # admission handled there (tenant quotas included)
            # Unary-through-stream still counts as a one-response
            # stream: its "first response" latency is the whole
            # request — so streaming load against non-decoupled
            # models populates the TTFT family too.
            now_ns = time.monotonic_ns()
            stats.record_stream_first(now_ns - t0)
            stats.record_stream_done()
            self.telemetry.observe_stream_first(
                model.name, (now_ns - t0) / 1000.0)
            stream_response = pb.ModelStreamInferResponse()
            stream_response.infer_response.CopyFrom(response)
            stream_response.infer_response.parameters[
                "triton_final_response"
            ].bool_param = True
            yield stream_response
            return
        # Decoupled: tenant quotas apply here too — the whole stream
        # spends one token and holds one in-flight slot for its
        # duration, so the streaming RPC cannot bypass admission. A
        # quota reject raises; the transports surface it as an
        # in-stream error.
        # The stream's CancelToken (mid-stream disconnect is THE
        # abandoned-LLM case): the model reads it from
        # params["cancel_token"] and reaps the lane between decode
        # chunks; the registry indexes it for wire cancellation.
        cancel = self._cancel_begin(request, cancel)
        with _TenantAdmission(self, request,
                              trace_context) as admission:
            # model came from repository.get above, so the name is
            # validated — per-model tenant rows are recorded even when
            # the in-flight acquire below fails (drain in progress).
            admission.model_name = model.name
            trace = None
            ftrace = None
            token = None
            acquired = False
            # The whole stream holds one in-flight admission so a
            # graceful unload drains it before teardown. Everything
            # past the quota acquire runs inside the admission scope so
            # an acquire/trace failure (model draining, bad version)
            # still returns the tenant's token and in-flight slot.
            try:
                try:
                    model = self.repository.acquire(
                        request.model_name, request.model_version)
                except InferenceServerException as e:
                    # Drain/unknown-model rejects on the stream path
                    # fire before the scratch capture below — retain
                    # them like the unary path does.
                    self._flight_admission_reject(request,
                                                  trace_context, e)
                    raise
                acquired = True
                self.hbm.touch_model(model.name)
                trace = self._trace_begin(model.name, trace_context,
                                          request.id)
                ftrace = trace
                if ftrace is None and self.flight.enabled:
                    # Flight scratch for unsampled streams (same tail
                    # sampling as the unary path; stream errors ride
                    # the stream as responses, so _stream_admitted
                    # stamps them on the root attrs for the keep
                    # decision below).
                    ftrace = spantrace.RequestTrace(
                        trace_context,
                        attrs={"model": model.name,
                               "request_id": request.id},
                        sampled=False)
                if ftrace is not None and self.flight.enabled:
                    token = self.flight.track(model.name, request.id,
                                              ftrace)
                yield from self._stream_admitted(model, request, stats,
                                                 t0, want_empty_final,
                                                 ftrace, cancel=cancel)
                admission.ok = True
            finally:
                if cancel is not None:
                    self.cancel.untrack(cancel)
                    if cancel.cancelled():
                        # One count per abandoned stream — whether the
                        # signal surfaced as an in-stream error or as
                        # a transport teardown closing this generator.
                        stats.record_cancelled(cancel.stage or "stream")
                        if ftrace is not None:
                            ftrace.root.attrs["cancelled"] = \
                                cancel.stage or "stream"
                if ftrace is not None:
                    attrs = ftrace.root.attrs or {}
                    stream_error = attrs.get("error")
                    stream_status = attrs.get("error_status")
                    ftrace.finish(error=stream_error)
                    if trace is not None:
                        self._trace_emit(model.name, request.id, trace)
                    # Streams keep only on error: their wall clock
                    # scales with response count by design, so the
                    # slow threshold would retain every long stream.
                    try:
                        self.flight.observe(
                            model, model.name, request.id, ftrace,
                            error=stream_error, status=stream_status,
                            token=token, allow_slow=False)
                    except Exception:  # noqa: BLE001 — a recorder
                        pass  # fault must never leak the acquisition
                    profiler = self.devstats.profiler
                    if profiler.armed:
                        profiler.tap(model.name, request.id, ftrace)
                if acquired:
                    self.repository.release(model.name)

    def _stream_admitted(self, model, request, stats, t0,
                         want_empty_final, trace=None, cancel=None):
        try:
            decode_span = (trace.begin(spantrace.SPAN_DECODE)
                           if trace is not None else None)
            inputs, params = self._decode_inputs(model, request)
            if decode_span is not None:
                trace.end(decode_span)
            if cancel is not None:
                # Models that own a scheduler (the LLM's continuous-
                # batching loop) react to the token directly: the lane
                # is reaped between decode chunks, pages/reservations
                # freed, instead of waiting for this consumer loop to
                # notice. cancel_token never enters cache keys or
                # fusion fingerprints (_UNCACHED_PARAMS / _QOS_PARAMS).
                params["cancel_token"] = cancel
            count = 0
            pending = None  # buffer one ahead so the last data response
            # can carry the final flag when empty finals are off
            telemetry = self.telemetry
            trace_id = spantrace.exemplar_id(trace)
            # TTFT measures from stream admission (t0, before decode)
            # — the server-side bound of what the client experiences;
            # later gaps measure production-to-production (the
            # server-observed inter-token latency, incl. encode and
            # any consumer backpressure of the previous response).
            prev_ns = t0
            mark_ns = time.monotonic_ns()
            for out in model.infer_stream(inputs, params):
                if cancel is not None and cancel.cancelled():
                    # Explicit-cancel streams end with an in-stream
                    # CANCELLED error (deadlines stay advisory mid-
                    # stream: a healthy long generation is not a
                    # timeout). Disconnects tear the generator down
                    # via GeneratorExit instead and never reach here.
                    cancel.raise_if_cancelled("stream")
                now_ns = time.monotonic_ns()
                if trace is not None:
                    # One span per decoupled response: model produce
                    # time since the previous response left this loop
                    # (the server-side view of inter-token latency).
                    trace.add_timed(
                        spantrace.SPAN_STREAM_RESPONSE, mark_ns,
                        now_ns, {"index": count})
                if count == 0:
                    stats.record_stream_first(now_ns - prev_ns)
                    telemetry.observe_stream_first(
                        model.name, (now_ns - prev_ns) / 1000.0,
                        trace_id)
                else:
                    stats.record_stream_gap(now_ns - prev_ns)
                    telemetry.observe_stream_gap(
                        model.name, (now_ns - prev_ns) / 1000.0,
                        trace_id)
                prev_ns = now_ns
                response = self._encode_response(model, request, out)
                stream_response = pb.ModelStreamInferResponse()
                stream_response.infer_response.CopyFrom(response)
                stream_response.infer_response.parameters[
                    "triton_final_response"
                ].bool_param = False
                count += 1
                if pending is not None:
                    yield pending
                pending = stream_response
                mark_ns = time.monotonic_ns()
            if want_empty_final or count == 0:
                if pending is not None:
                    yield pending
                final = pb.ModelStreamInferResponse()
                final.infer_response.model_name = model.name
                final.infer_response.model_version = model.version
                final.infer_response.id = request.id
                final.infer_response.parameters[
                    "triton_final_response"
                ].bool_param = True
                yield final
            else:
                pending.infer_response.parameters[
                    "triton_final_response"
                ].bool_param = True
                yield pending
            stats.record_stream_done()
            stats.record(max(count, 1), 0, 0, time.monotonic_ns() - t0, 0, ok=True)
        except InferenceServerException as e:
            stats.record(1, 0, 0, time.monotonic_ns() - t0, 0, ok=False)
            if trace is not None:
                # Stream errors ride the stream, never raise — stamp
                # the root attrs so the flight recorder's retroactive
                # keep decision (and the emitted trace record) still
                # see the failure.
                trace.root.attrs["error"] = str(e)
                trace.root.attrs["error_status"] = e.status()
            yield stream_error_response(request, str(e))
        except Exception as e:
            stats.record(1, 0, 0, time.monotonic_ns() - t0, 0, ok=False)
            if trace is not None:
                trace.root.attrs["error"] = "inference failed: %s" % e
                trace.root.attrs["error_status"] = "INTERNAL"
            yield stream_error_response(request, "inference failed: %s" % e)

    # -- shared memory verbs --------------------------------------------

    def register_system_shm(self, name, key, offset, byte_size):
        self.memory.register_system(name, key, offset, byte_size)

    def unregister_system_shm(self, name):
        self.memory.unregister_system(name)

    def system_shm_status(self, name=""):
        return self.memory.system_status(name)

    def register_tpu_shm(self, name, raw_handle, device_id, byte_size):
        self.memory.register_tpu(name, raw_handle, device_id, byte_size)

    def unregister_tpu_shm(self, name):
        self.memory.unregister_tpu(name)

    def tpu_shm_status(self, name=""):
        return self.memory.tpu_status(name)

    # -- internals -------------------------------------------------------

    def _batch_size(self, model: ServedModel, request: pb.ModelInferRequest) -> int:
        if model.max_batch_size > 0 and request.inputs:
            shape = request.inputs[0].shape
            if shape:
                return max(int(shape[0]), 1)
        return 1

    def _decode_inputs(self, model: ServedModel, request: pb.ModelInferRequest):
        params = {k: _param_value(v) for k, v in request.parameters.items()}
        inputs: Dict[str, np.ndarray] = {}
        raw_idx = 0
        for tensor in request.inputs:
            spec = model.find_input(tensor.name)
            if spec is None:
                raise InferenceServerException(
                    "unexpected inference input '%s' for model '%s'"
                    % (tensor.name, model.name),
                    status="INVALID_ARGUMENT",
                )
            if tensor.datatype != spec.datatype:
                raise InferenceServerException(
                    "input '%s' has datatype %s, model '%s' expects %s"
                    % (tensor.name, tensor.datatype, model.name, spec.datatype),
                    status="INVALID_ARGUMENT",
                )
            shape = [int(d) for d in tensor.shape]
            unbatched = shape[1:] if model.max_batch_size > 0 else shape
            if not spec.compatible_with(unbatched):
                raise InferenceServerException(
                    "input '%s' has shape %s, model '%s' expects %s%s"
                    % (
                        tensor.name,
                        shape,
                        model.name,
                        "[batch] + " if model.max_batch_size > 0 else "",
                        spec.shape,
                    ),
                    status="INVALID_ARGUMENT",
                )
            if "shared_memory_region" in tensor.parameters:
                region = tensor.parameters["shared_memory_region"].string_param
                byte_size = tensor.parameters[
                    "shared_memory_byte_size"
                ].int64_param
                offset = (
                    tensor.parameters["shared_memory_offset"].int64_param
                    if "shared_memory_offset" in tensor.parameters
                    else 0
                )
                inputs[tensor.name] = self.memory.read_input(
                    region, byte_size, offset, tensor.datatype, shape
                )
            elif tensor.HasField("contents") and (
                len(tensor.contents.bool_contents)
                or len(tensor.contents.int_contents)
                or len(tensor.contents.int64_contents)
                or len(tensor.contents.uint_contents)
                or len(tensor.contents.uint64_contents)
                or len(tensor.contents.fp32_contents)
                or len(tensor.contents.fp64_contents)
                or len(tensor.contents.bytes_contents)
            ):
                inputs[tensor.name] = _from_contents(tensor, shape)
            else:
                if raw_idx >= len(request.raw_input_contents):
                    raise InferenceServerException(
                        "input '%s' has no data" % tensor.name,
                        status="INVALID_ARGUMENT",
                    )
                raw = request.raw_input_contents[raw_idx]
                raw_idx += 1
                inputs[tensor.name] = _decode_raw(
                    raw, tensor.datatype, shape, tensor.name
                )
        # missing non-optional inputs?
        for spec in model.inputs:
            if spec.name not in inputs and not spec.optional:
                raise InferenceServerException(
                    "input '%s' is required by model '%s'"
                    % (spec.name, model.name),
                    status="INVALID_ARGUMENT",
                )
        return inputs, params

    def _encode_response(
        self,
        model: ServedModel,
        request: pb.ModelInferRequest,
        outputs: Dict[str, np.ndarray],
    ) -> pb.ModelInferResponse:
        response = pb.ModelInferResponse(
            model_name=model.name, model_version=model.version, id=request.id
        )
        requested = list(request.outputs)
        if not requested:
            names = list(outputs.keys())
        else:
            names = [t.name for t in requested]
        req_by_name = {t.name: t for t in requested}
        for name in names:
            if name not in outputs:
                raise InferenceServerException(
                    "unexpected inference output '%s' for model '%s'"
                    % (name, model.name),
                    status="INVALID_ARGUMENT",
                )
            value = outputs[name]
            req = req_by_name.get(name)
            cls_count = 0
            if req is not None and "classification" in req.parameters:
                cls_count = int(req.parameters["classification"].int64_param)
            if cls_count:
                value = _classification(np.asarray(value), cls_count)
            arr = value
            # dtype/shape come from the array metadata — never force a
            # device->host transfer for shm-placed outputs
            datatype = np_to_wire_dtype(arr.dtype)
            tensor = response.outputs.add()
            tensor.name = name
            tensor.datatype = datatype
            tensor.shape.extend(int(d) for d in arr.shape)
            if req is not None and "shared_memory_region" in req.parameters:
                region = req.parameters["shared_memory_region"].string_param
                byte_size = req.parameters["shared_memory_byte_size"].int64_param
                offset = (
                    req.parameters["shared_memory_offset"].int64_param
                    if "shared_memory_offset" in req.parameters
                    else 0
                )
                written = self.memory.write_output(
                    region, byte_size, offset, arr
                )
                tensor.parameters["shared_memory_region"].string_param = region
                tensor.parameters["shared_memory_byte_size"].int64_param = written
                if offset:
                    tensor.parameters["shared_memory_offset"].int64_param = offset
            else:
                np_arr = arr if isinstance(arr, np.ndarray) else np.asarray(arr)
                if datatype == "BYTES":
                    raw = serialize_byte_tensor(np_arr).tobytes()
                elif datatype == "BF16":
                    raw = serialize_bf16_tensor(np_arr).tobytes()
                else:
                    raw = np.ascontiguousarray(np_arr).tobytes()
                response.raw_output_contents.append(raw)
        return response


def _decode_raw(raw: bytes, datatype: str, shape, name: str) -> np.ndarray:
    try:
        if datatype == "BYTES":
            return deserialize_bytes_tensor(raw).reshape(shape)
        if datatype == "BF16":
            return deserialize_bf16_tensor(raw).reshape(shape)
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise InferenceServerException(
                "unknown datatype '%s'" % datatype, status="INVALID_ARGUMENT"
            )
        return np.frombuffer(raw, dtype=np_dtype).reshape(shape)
    except ValueError as e:
        raise InferenceServerException(
            "unable to decode input '%s': %s" % (name, e),
            status="INVALID_ARGUMENT",
        )


def _from_contents(tensor: pb.ModelInferRequest.InferInputTensor, shape):
    c = tensor.contents
    dt = tensor.datatype
    if dt == "BOOL":
        arr = np.array(c.bool_contents, dtype=np.bool_)
    elif dt in ("INT8", "INT16", "INT32"):
        arr = np.array(c.int_contents, dtype=triton_to_np_dtype(dt))
    elif dt == "INT64":
        arr = np.array(c.int64_contents, dtype=np.int64)
    elif dt in ("UINT8", "UINT16", "UINT32"):
        arr = np.array(c.uint_contents, dtype=triton_to_np_dtype(dt))
    elif dt == "UINT64":
        arr = np.array(c.uint64_contents, dtype=np.uint64)
    elif dt in ("FP16", "FP32", "BF16"):
        arr = np.array(c.fp32_contents, dtype=triton_to_np_dtype(dt))
    elif dt == "FP64":
        arr = np.array(c.fp64_contents, dtype=np.float64)
    elif dt == "BYTES":
        arr = np.array(list(c.bytes_contents), dtype=np.object_)
    else:
        raise InferenceServerException(
            "unknown datatype '%s'" % dt, status="INVALID_ARGUMENT"
        )
    return arr.reshape(shape)


def _classification(value: np.ndarray, k: int) -> np.ndarray:
    """Top-k classification strings "score:index" over the last axis
    (v2 classification extension)."""
    flat = value.reshape(-1, value.shape[-1]) if value.ndim > 1 else value[None, :]
    k = min(k, flat.shape[-1])
    rows = []
    for row in flat:
        idx = np.argsort(row)[::-1][:k]
        rows.append([("%f:%d" % (row[i], i)).encode() for i in idx])
    out = np.array(rows, dtype=np.object_)
    if value.ndim > 1:
        return out.reshape(value.shape[:-1] + (k,))
    return out.reshape(k)
