"""Always-on latency histograms + streaming-token telemetry.

The server-side SLO layer the span tracer (client_tpu.server.tracing)
cannot be: tracing samples 1-in-N requests and renders a span tree per
sample — perfect for attributing ONE slow request, useless as a
continuously scraped p99. This module keeps fixed-bucket, log-spaced
latency histograms for EVERY request at every serving stage the span
tree delineates, cheap enough to stay on at trace_rate=0, and exposes
them as proper Prometheus histogram families
(``_bucket{le=...}`` / ``_sum`` / ``_count``):

* ``tpu_request_duration_us{model=...}`` — end-to-end served requests
  (success paths only: cache hits, scheduler paths, direct executes).
* ``tpu_stage_duration_us{model=...,stage=...}`` — per-stage time.
  Per-request stages (``decode`` / ``queue`` / ``execute`` /
  ``encode``) tile the request like the span tree's timeline; the
  dynamic batcher adds per-fused-execution stages (``batch_execute``
  / ``relay_fetch``) — one observation per fused batch, not per
  member request.
* ``tpu_stream_first_response_us{model=...}`` — server-observed time
  to first streamed response (TTFT for token streams), measured from
  stream admission to the model producing its first response.
* ``tpu_stream_inter_response_us{model=...}`` — server-observed gap
  between consecutive streamed responses (inter-token latency for
  one-token-per-response LLM streams).
* ``tpu_stream_responses_total{model=...}`` — responses streamed.
* ``tpu_tenant_request_duration_us{tenant=...}`` — per-tenant
  end-to-end histogram (replaces the PR-7 sum-only counter, whose
  rate() had no paired count to divide by).

Design constraints:

* **Lock-cheap.** One observation is a bisect on a shared immutable
  bounds tuple plus three integer updates under a per-histogram lock
  (never the server's stats lock); the bench's telemetry_overhead
  stage gates the cost at <2% throughput with histograms always on.
* **Fixed buckets.** A 1-2-5 ladder from 1 us to 10 s. Log-spaced
  buckets keep relative quantile-estimation error bounded at every
  scale (a 100 us CPU model and a 10 s LLM decode share one ladder),
  and fixed bounds mean scrapes are mergeable across models, windows,
  and servers.
* **Trace-joinable.** When the observed request was trace-sampled,
  the bucket it lands in keeps an OpenMetrics-style exemplar
  (``# {trace_id="..."} value timestamp``) — a dashboard's p99
  outlier bucket links straight to the span tree that explains it.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple

# Bucket upper bounds in MICROSECONDS: a 1-2-5 ladder from 1 us to
# 10 s, +Inf implied as the final bucket. Shared by every histogram so
# scrapes merge and the perf harness can estimate quantiles without
# reading bounds out of band.
DEFAULT_BOUNDS_US: Tuple[float, ...] = (
    1, 2, 5, 10, 20, 50, 100, 200, 500,
    1_000, 2_000, 5_000, 10_000, 20_000, 50_000,
    100_000, 200_000, 500_000,
    1_000_000, 2_000_000, 5_000_000, 10_000_000,
)

INF = float("inf")


def bucket_width_us(value_us: float,
                    bounds: Tuple[float, ...] = DEFAULT_BOUNDS_US
                    ) -> float:
    """Width of the bucket containing ``value_us`` — the resolution
    bound tests hold quantile estimates to."""
    idx = bisect_left(bounds, value_us)
    if idx >= len(bounds):
        return INF
    lower = bounds[idx - 1] if idx > 0 else 0.0
    return bounds[idx] - lower


def format_le(bound: float) -> str:
    """Prometheus ``le`` label value: integers render bare, +Inf as
    the literal ``+Inf``."""
    if bound == INF:
        return "+Inf"
    if bound == int(bound):
        return "%d" % int(bound)
    return repr(bound)


class LatencyHistogram:
    """One fixed-bucket latency accumulator (values in microseconds).

    ``observe`` is the hot path: bisect against the shared bounds
    (outside the lock — bounds are immutable), then three updates
    under the histogram's own lock. Exemplars are kept per bucket,
    last-writer-wins: the freshest trace-sampled request to land in a
    bucket is the one a dashboard wants to open anyway."""

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock",
                 "_exemplars")

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_BOUNDS_US):
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        # bucket index -> (trace_id, observed value, unix seconds)
        self._exemplars: Dict[int, Tuple[str, float, float]] = {}

    def observe(self, value_us: float,
                trace_id: Optional[str] = None) -> None:
        if value_us < 0:
            value_us = 0.0
        idx = bisect_left(self.bounds, value_us)
        if trace_id is None:
            with self._lock:
                self._counts[idx] += 1
                self._sum += value_us
                self._count += 1
        else:
            # time.time() outside the lock: exemplar timestamps are
            # wall-clock for dashboard display, not ordering.
            stamp = (trace_id, value_us, time.time())
            with self._lock:
                self._counts[idx] += 1
                self._sum += value_us
                self._count += 1
                self._exemplars[idx] = stamp

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative_count), ...], "sum": float,
        "count": int, "exemplars": {le: (trace_id, value, ts)}}`` —
        buckets are CUMULATIVE (Prometheus semantics) and always end
        at +Inf."""
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
            total = self._count
            exemplars = dict(self._exemplars)
        buckets: List[Tuple[float, int]] = []
        running = 0
        for bound, count in zip(self.bounds, counts):
            running += count
            buckets.append((bound, running))
        buckets.append((INF, running + counts[-1]))
        return {
            "buckets": buckets,
            "sum": total_sum,
            "count": total,
            "exemplars": {
                (self.bounds[idx] if idx < len(self.bounds) else INF):
                    exemplar
                for idx, exemplar in exemplars.items()
            },
        }


def estimate_quantile(buckets: Iterable[Tuple[float, float]],
                      q: float) -> float:
    """Quantile estimate (same value space as the bounds, us here)
    from CUMULATIVE ``(le, count)`` pairs — the classic
    histogram_quantile(): find the bucket holding rank ``q * total``
    and interpolate linearly inside it. The +Inf bucket clamps to the
    highest finite bound (an estimate beyond the ladder is a lie; the
    clamp at least says "at or past the top"). Returns 0.0 for an
    empty histogram."""
    pairs = sorted(buckets, key=lambda pair: pair[0])
    if not pairs:
        return 0.0
    total = pairs[-1][1]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in pairs:
        if cum >= rank:
            if bound == INF:
                return prev_bound
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return bound
            fraction = (rank - prev_cum) / in_bucket
            return prev_bound + (bound - prev_bound) * fraction
        prev_bound, prev_cum = bound, cum
    return prev_bound


class _Counter:
    """A monotonically increasing counter with its own small lock."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def value(self) -> int:
        with self._lock:
            return self._value


class ModelTelemetry:
    """Per-model histogram set (request + stages + stream)."""

    __slots__ = ("request", "stages", "stream_first", "stream_inter",
                 "stream_responses", "ensemble_steps",
                 "ensemble_fused", "ensemble_cache_hits", "_stage_lock")

    def __init__(self):
        self.request = LatencyHistogram()
        self.stages: Dict[str, LatencyHistogram] = {}
        self.stream_first = LatencyHistogram()
        self.stream_inter = LatencyHistogram()
        self.stream_responses = _Counter()
        # Device-resident ensemble dataflow: per-step duration
        # histograms keyed "<index>:<composing model>", plus fused
        # (non-leader) step executions and composing-cache
        # short-circuits. Only ensembles populate these.
        self.ensemble_steps: Dict[str, LatencyHistogram] = {}
        self.ensemble_fused = _Counter()
        self.ensemble_cache_hits = _Counter()
        self._stage_lock = threading.Lock()

    def stage(self, name: str) -> LatencyHistogram:
        hist = self.stages.get(name)
        if hist is None:
            with self._stage_lock:
                hist = self.stages.get(name)
                if hist is None:
                    hist = LatencyHistogram()
                    self.stages[name] = hist
        return hist

    def ensemble_step(self, step: str) -> LatencyHistogram:
        hist = self.ensemble_steps.get(step)
        if hist is None:
            with self._stage_lock:
                hist = self.ensemble_steps.get(step)
                if hist is None:
                    hist = LatencyHistogram()
                    self.ensemble_steps[step] = hist
        return hist

    def stages_snapshot(self) -> Dict[str, LatencyHistogram]:
        """Copy of the stage map for iteration: a concurrent first
        observation of a new stage mutates ``stages`` mid-scrape, and
        iterating the live dict would raise."""
        with self._stage_lock:
            return dict(self.stages)

    def ensemble_steps_snapshot(self) -> Dict[str, LatencyHistogram]:
        with self._stage_lock:
            return dict(self.ensemble_steps)


class ServerTelemetry:
    """The server-wide registry: one ModelTelemetry per model plus the
    per-tenant duration histograms. ``enabled=False`` turns every
    observe into a cheap early return — the A/B arm the
    telemetry_overhead bench stage measures against; the
    ``CLIENT_TPU_TELEMETRY`` env var (``off``/``0``/``false``)
    disables it for embedded launches with no ctor surface."""

    # Tenant identity is client-supplied: past this cap new names fold
    # into the shared overflow row (same bound as qos.py's tracked
    # tenants) so a rotating header cannot grow /metrics unboundedly.
    MAX_TENANTS = 1024
    OVERFLOW_TENANT = "overflow"

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            import os

            enabled = os.environ.get(
                "CLIENT_TPU_TELEMETRY", "").strip().lower() not in (
                    "off", "0", "false", "disabled")
        self.enabled = bool(enabled)
        self._models: Dict[str, ModelTelemetry] = {}
        self._tenants: Dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()

    # -- recording --------------------------------------------------------

    def for_model(self, model_name: str) -> ModelTelemetry:
        telemetry = self._models.get(model_name)
        if telemetry is None:
            with self._lock:
                telemetry = self._models.get(model_name)
                if telemetry is None:
                    telemetry = ModelTelemetry()
                    self._models[model_name] = telemetry
        return telemetry

    def observe_request(self, model_name: str, us: float,
                        trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self.for_model(model_name).request.observe(us, trace_id)

    def observe_stage(self, model_name: str, stage: str, us: float,
                      trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self.for_model(model_name).stage(stage).observe(us, trace_id)

    def observe_stream_first(self, model_name: str, us: float,
                             trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        telemetry = self.for_model(model_name)
        telemetry.stream_first.observe(us, trace_id)
        telemetry.stream_responses.add(1)

    def observe_stream_gap(self, model_name: str, us: float,
                           trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        telemetry = self.for_model(model_name)
        telemetry.stream_inter.observe(us, trace_id)
        telemetry.stream_responses.add(1)

    def observe_ensemble_step(self, model_name: str, step: str,
                              us: float,
                              trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self.for_model(model_name).ensemble_step(step).observe(
            us, trace_id)

    def record_ensemble_fused(self, model_name: str,
                              n: int = 1) -> None:
        if not self.enabled:
            return
        self.for_model(model_name).ensemble_fused.add(n)

    def record_ensemble_cache_hit(self, model_name: str,
                                  n: int = 1) -> None:
        if not self.enabled:
            return
        self.for_model(model_name).ensemble_cache_hits.add(n)

    def observe_tenant(self, tenant: str, us: float) -> None:
        if not self.enabled:
            return
        hist = self._tenants.get(tenant)
        if hist is None:
            with self._lock:
                hist = self._tenants.get(tenant)
                if hist is None:
                    if len(self._tenants) >= self.MAX_TENANTS:
                        tenant = self.OVERFLOW_TENANT
                    hist = self._tenants.setdefault(tenant,
                                                    LatencyHistogram())
        hist.observe(us)

    # -- exposition -------------------------------------------------------

    @staticmethod
    def _exemplar_suffix(exemplars: dict, le: float) -> str:
        entry = exemplars.get(le)
        if entry is None:
            return ""
        trace_id, value, stamp = entry
        return ' # {trace_id="%s"} %s %.3f' % (trace_id, repr(float(value)),
                                               stamp)

    @classmethod
    def _histogram_rows(cls, family: str, label: str, snapshot: dict,
                        with_exemplars: bool = True) -> List[str]:
        rows = []
        exemplars = snapshot["exemplars"] if with_exemplars else {}
        for le, cumulative in snapshot["buckets"]:
            rows.append('%s_bucket{%s,le="%s"} %d%s'
                        % (family, label, format_le(le), cumulative,
                           cls._exemplar_suffix(exemplars, le)))
        rows.append("%s_sum{%s} %s" % (family, label,
                                       repr(float(snapshot["sum"]))))
        rows.append("%s_count{%s} %d" % (family, label,
                                         snapshot["count"]))
        return rows

    def render(self, escape=None, exemplars: bool = True) -> List[str]:
        """Exposition lines for every non-empty histogram family
        (HELP/TYPE included; empty families are omitted entirely so
        an idle server's scrape stays small). ``escape`` sanitizes
        client-supplied tenant label values. ``exemplars=False``
        suppresses the OpenMetrics exemplar suffixes — the core passes
        the current tracing state here, so the exposition returns to
        strict text-format 0.0.4 the moment tracing is disabled
        (stored exemplars are retained, not re-emitted)."""
        if escape is None:
            escape = lambda value: str(value)  # noqa: E731
        with self._lock:
            models = dict(self._models)
            tenants = dict(self._tenants)
        lines: List[str] = []

        def family(name, help_text, rows, kind="histogram"):
            if not rows:
                return
            lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)

        request_rows: List[str] = []
        stage_rows: List[str] = []
        first_rows: List[str] = []
        inter_rows: List[str] = []
        response_rows: List[str] = []
        step_rows: List[str] = []
        fused_rows: List[str] = []
        cache_hit_rows: List[str] = []
        for name in sorted(models):
            telemetry = models[name]
            label = 'model="%s"' % name
            snap = telemetry.request.snapshot()
            if snap["count"]:
                request_rows.extend(self._histogram_rows(
                    "tpu_request_duration_us", label, snap,
                    exemplars))
            stages = telemetry.stages_snapshot()
            for stage in sorted(stages):
                snap = stages[stage].snapshot()
                if snap["count"]:
                    stage_rows.extend(self._histogram_rows(
                        "tpu_stage_duration_us",
                        '%s,stage="%s"' % (label, stage), snap,
                        exemplars))
            steps = telemetry.ensemble_steps_snapshot()
            for step in sorted(steps):
                snap = steps[step].snapshot()
                if snap["count"]:
                    step_rows.extend(self._histogram_rows(
                        "tpu_ensemble_step_duration_us",
                        '%s,step="%s"' % (label, step), snap,
                        exemplars))
            fused = telemetry.ensemble_fused.value()
            if fused:
                fused_rows.append(
                    "tpu_ensemble_fused_total{%s} %d" % (label, fused))
            hits = telemetry.ensemble_cache_hits.value()
            if hits:
                cache_hit_rows.append(
                    "tpu_ensemble_cache_hits_total{%s} %d"
                    % (label, hits))
            snap = telemetry.stream_first.snapshot()
            if snap["count"]:
                first_rows.extend(self._histogram_rows(
                    "tpu_stream_first_response_us", label, snap,
                    exemplars))
            snap = telemetry.stream_inter.snapshot()
            if snap["count"]:
                inter_rows.extend(self._histogram_rows(
                    "tpu_stream_inter_response_us", label, snap,
                    exemplars))
            responses = telemetry.stream_responses.value()
            if responses:
                response_rows.append(
                    "tpu_stream_responses_total{%s} %d"
                    % (label, responses))
        family("tpu_request_duration_us",
               "End-to-end served request duration (histogram; "
               "success paths incl. cache hits)", request_rows)
        family("tpu_stage_duration_us",
               "Per-stage serving time (histogram; per-request stages "
               "decode/queue/execute/encode tile the request, "
               "batch_execute/relay_fetch are per fused execution)",
               stage_rows)
        family("tpu_stream_first_response_us",
               "Server-observed time to first streamed response "
               "(TTFT for token streams)", first_rows)
        family("tpu_stream_inter_response_us",
               "Server-observed gap between consecutive streamed "
               "responses (inter-token latency for token streams)",
               inter_rows)
        family("tpu_stream_responses_total",
               "Responses streamed by decoupled/stream inference",
               response_rows, kind="counter")
        family("tpu_ensemble_step_duration_us",
               "Per-stage device-resident ensemble dataflow time "
               "(histogram; step label is <index>:<composing model>, "
               "measured queue+execute per stage)", step_rows)
        family("tpu_ensemble_fused_total",
               "Composing-model step executions that fused into "
               "another request's batch (non-leader batcher rides)",
               fused_rows, kind="counter")
        family("tpu_ensemble_cache_hits_total",
               "Ensemble subgraphs short-circuited by a composing-"
               "model response-cache hit", cache_hit_rows,
               kind="counter")

        tenant_rows: List[str] = []
        for tenant in sorted(tenants):
            snap = tenants[tenant].snapshot()
            if snap["count"]:
                tenant_rows.extend(self._histogram_rows(
                    "tpu_tenant_request_duration_us",
                    'tenant="%s"' % escape(tenant), snap, exemplars))
        family("tpu_tenant_request_duration_us",
               "End-to-end successful request duration per tenant "
               "(histogram; replaces the sum-only counter)",
               tenant_rows)
        return lines
