"""Per-model autoscale controller — the loop that closes the loop.

Every control signal this module consumes already existed: live queue
depth (`DynamicBatcher.stats_snapshot` / tpu_queue_size), per-device
duty cycle (devstats), and the SLO engine's multi-window burn-rate
verdicts. What was missing was the actuator: a feedback controller
that reads those signals on a background tick and drives the
`ReplicaSet` between the `instance_group` autoscale bounds.

Decision ladder, evaluated per model per tick:

* **Scale up** when queue depth per healthy replica exceeds
  ``queue_high``, device duty cycle exceeds ``duty_high``, or the SLO
  verdict is unhealthy — bounded by ``max_replicas`` and the
  ``up_cooldown_s`` hysteresis. The new replica is warmed and
  canaried through the chaos-injected execution path (the PR-8
  supervisor readmission flow) BEFORE it enters routing: a sick birth
  never sees traffic.
* **Shed directive** when the SLO burns even AT max scale: growing is
  no longer an option, so a `qos.ShedDirective` is installed on the
  batcher and lowest-priority arrivals shed at the door (the PR-7
  watermark path) with a Retry-After derived from the controller's
  predicted recovery time (queued work / healthy service rate).
  Cleared the first tick the verdict recovers.
* **Scale down** when the model is quiet — empty queue, duty below
  ``duty_low``, fast burn under 1 — sustained past ``down_cooldown_s``;
  the victim replica drains through the existing routing tail.
* **Scale to zero** when ``min_replicas == 0`` and the model has been
  completely idle for ``idle_s``: the model unloads entirely (the HBM
  ledger shows exactly whose memory frees) and the controller
  remembers it. The next arrival triggers a transparent cold start —
  a background reload plus an honest 503 + Retry-After while warming.

Every decision is stamped into the flight recorder twice: as a
standalone ring record (`record_decision`, the auditable evidence) and
as an incident stamp on resident traces (`mark_incident`, joining the
decision to the requests that provoked it), and counted in the
`tpu_scale_events_total{model,direction,reason}` family next to the
`tpu_replica_desired{model}` gauge and the
`tpu_replica_seconds_total{model}` cost counter.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from client_tpu.server import devstats as devstats_mod
from client_tpu.server import qos

_LOG = logging.getLogger("client_tpu.server.autoscale")

# Control-loop pace when the model declares none (interval_s == 0).
DEFAULT_INTERVAL_S = 1.0
# Fallback warm-time estimate for the first cold start (no measured
# load yet); replaced by the measured reload time afterwards.
DEFAULT_WARM_ESTIMATE_S = 1.0
# Clamp band for the shed directive's predicted recovery time.
MIN_RETRY_AFTER_S = 0.1
MAX_RETRY_AFTER_S = 10.0


class _ModelState:
    """Mutable per-model controller memory (owned by the tick thread;
    read-only snapshots cross threads under the controller lock)."""

    __slots__ = ("desired", "last_up", "last_down", "idle_since",
                 "last_inference_count", "last_decision", "last_reason",
                 "last_decision_ts", "replica_seconds", "events",
                 "shed", "last_seen")

    def __init__(self) -> None:
        self.desired = 0
        self.last_up = 0.0
        self.last_down = 0.0
        self.idle_since: Optional[float] = None
        self.last_inference_count = 0
        self.last_decision = "none"
        self.last_reason = ""
        self.last_decision_ts = 0.0
        self.replica_seconds = 0.0
        # (direction, reason) -> cumulative count, feeds
        # tpu_scale_events_total{model,direction,reason}.
        self.events: Dict[tuple, int] = {}
        self.shed = qos.ShedDirective()
        self.last_seen = 0.0


class _ColdModel:
    """A model the controller scaled to zero: enough memory to answer
    its next arrival honestly (kick one reload/restore, estimate warm
    time). ``mode`` records HOW it went cold — "paged" (weights live
    on host via the hbm allocator; warming is a restore) or
    "unloaded" (full teardown; warming is a factory reload)."""

    __slots__ = ("warm_estimate_s", "loading", "load_started", "mode")

    def __init__(self, warm_estimate_s: float,
                 mode: str = "unloaded") -> None:
        self.warm_estimate_s = warm_estimate_s
        self.loading = False
        self.load_started = 0.0
        self.mode = mode


class AutoscaleController:
    """Background feedback loop over every autoscale-enabled model.

    Created unconditionally by the core; the thread starts lazily on
    the first `ensure_started()` (a model with an autoscale block was
    loaded or touched), so servers without autoscaling pay nothing."""

    def __init__(self, core) -> None:
        self._core = core
        self._lock = threading.Lock()
        self._states: Dict[str, _ModelState] = {}
        self._cold: Dict[str, _ColdModel] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_tick = 0.0

    # -- lifecycle ---------------------------------------------------------

    def ensure_started(self) -> None:
        with self._lock:
            if self._thread is not None or self._stop.is_set():
                return
            self._thread = threading.Thread(
                target=self._run, name="autoscale-controller",
                daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5)

    # -- configuration -----------------------------------------------------

    @staticmethod
    def config_of(model) -> Optional[dict]:
        """The model's autoscale knobs, or None when the controller is
        off for it (max_replicas unset)."""
        max_replicas = int(getattr(model, "autoscale_max_replicas", 0))
        if max_replicas <= 0:
            return None
        return {
            "min_replicas": max(
                int(getattr(model, "autoscale_min_replicas", 0)), 0),
            "max_replicas": max_replicas,
            "interval_s": float(
                getattr(model, "autoscale_interval_s", 0.0))
            or DEFAULT_INTERVAL_S,
            "queue_high": float(
                getattr(model, "autoscale_queue_high", 0.0)),
            "duty_high": float(
                getattr(model, "autoscale_duty_high", 0.0)),
            "duty_low": float(
                getattr(model, "autoscale_duty_low", 0.0)),
            "up_cooldown_s": float(
                getattr(model, "autoscale_up_cooldown_s", 0.0)),
            "down_cooldown_s": float(
                getattr(model, "autoscale_down_cooldown_s", 0.0)),
            "idle_s": float(getattr(model, "autoscale_idle_s", 0.0)),
        }

    # -- control loop ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            interval = DEFAULT_INTERVAL_S
            try:
                interval = self.tick_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                _LOG.exception("autoscale tick failed")
            self._stop.wait(max(interval, 0.05))

    def tick_once(self) -> float:
        """One full evaluation pass over every autoscale-enabled ready
        model. Returns the next sleep interval (the smallest declared
        interval among governed models). Public so tests can drive
        the controller deterministically without the thread."""
        core = self._core
        now = time.monotonic()
        dt = (now - self._last_tick) if self._last_tick else 0.0
        self._last_tick = now
        try:
            duty_by_device = devstats_mod.get().duty_cycle()
            duty = max(duty_by_device.values()) if duty_by_device else 0.0
        except Exception:  # noqa: BLE001
            duty = 0.0
        verdicts: Dict[str, dict] = {}
        interval = DEFAULT_INTERVAL_S
        governed = []
        for model in core.repository.ready_models():
            config = self.config_of(model)
            if config is None:
                continue
            governed.append((model, config))
            interval = min(interval, config["interval_s"])
        if governed:
            try:
                verdicts = core.slo.cached_verdicts(
                    max_age_s=interval)
            except Exception:  # noqa: BLE001
                verdicts = {}
        for model, config in governed:
            try:
                self._tick_model(model.name, config,
                                 verdicts.get(model.name), duty,
                                 now, dt)
            except Exception:  # noqa: BLE001 — one sick model must
                _LOG.exception(  # not stall the others' control loop
                    "autoscale tick for '%s' failed", model.name)
        return interval

    def _tick_model(self, name: str, config: dict,
                    verdict: Optional[dict], duty: float,
                    now: float, dt: float) -> None:
        core = self._core
        with self._lock:
            state = self._states.setdefault(name, _ModelState())
            state.last_seen = now
        with core._replica_lock:
            replica_set = core._replica_sets.get(name)
        with core._batchers_lock:
            batcher = core._batchers.get(name)
        pending = 0
        if batcher is not None:
            try:
                pending = int(
                    batcher.stats_snapshot()["pending_count"])
            except Exception:  # noqa: BLE001
                pending = 0
        snap = replica_set.snapshot() if replica_set else None
        actual = snap["count"] if snap else 0
        healthy = snap["healthy"] if snap else 0
        if dt > 0:
            # Cost accounting: what the fleet actually consumed this
            # interval (tpu_replica_seconds_total — the number the
            # smoke gates against max-scale-always).
            state.replica_seconds += actual * dt
        inference_count = core._stats_for(name).inference_count
        # An unmonitored verdict is unhealthy-by-design for alerting,
        # but the controller must not chase capacity it cannot
        # observe: only a MONITORED unhealthy verdict is SLO pressure.
        slo_pressure = bool(verdict
                            and verdict.get("monitored", True)
                            and not verdict["healthy"])
        fast_burn = (verdict["burn"]["fast"] if verdict else 0.0)
        state.desired = max(actual, config["min_replicas"]) \
            if actual else state.desired

        # -- idle tracking (scale-to-zero arm) ---------------------------
        busy = pending > 0 \
            or inference_count != state.last_inference_count
        state.last_inference_count = inference_count
        if busy:
            state.idle_since = None
        elif state.idle_since is None:
            state.idle_since = now

        # -- scale up ----------------------------------------------------
        reason = None
        if replica_set is not None and actual < config["max_replicas"]:
            if config["queue_high"] > 0 \
                    and pending > config["queue_high"] * max(healthy, 1):
                reason = "queue_depth"
            elif config["duty_high"] > 0 and duty > config["duty_high"]:
                reason = "duty_cycle"
            elif slo_pressure:
                reason = "slo_burn"
            if reason is not None \
                    and now - state.last_up >= config["up_cooldown_s"]:
                state.desired = actual + 1
                state.last_up = now
                if replica_set.scale_up():
                    self._decide(state, name, "up", reason,
                                 {"from": actual, "to": actual + 1,
                                  "pending": pending,
                                  "duty": round(duty, 3),
                                  "fast_burn": round(fast_burn, 3)})
                else:
                    # Canary rejected the prospect (or the set was
                    # stopping): the fleet is unchanged and the audit
                    # trail must say a grow was attempted and why it
                    # did not land.
                    state.desired = actual
                    self._decide(state, name, "up", "canary_rejected",
                                 {"from": actual, "to": actual,
                                  "wanted": reason})
                return

        # -- shed directive (SLO unmeetable at max scale) ----------------
        if replica_set is not None and slo_pressure \
                and actual >= config["max_replicas"]:
            retry_after = self._predicted_recovery_s(snap, pending)
            directive = qos.ShedDirective(
                active=True, retry_after_s=retry_after,
                reason="slo unmeetable at max scale %d"
                % config["max_replicas"],
                since=state.shed.since or time.time())
            first = not state.shed.active
            state.shed = directive
            if batcher is not None:
                batcher.set_shed_directive(directive)
            if first:
                self._decide(state, name, "shed", "slo_unmeetable",
                             {"retry_after_s": round(retry_after, 3),
                              "at_scale": actual})
            return
        if state.shed.active and not slo_pressure:
            state.shed = qos.ShedDirective()
            if batcher is not None:
                batcher.set_shed_directive(None)
            self._decide(state, name, "shed_clear", "slo_recovered", {})

        # -- scale down / scale to zero ----------------------------------
        quiet = (pending == 0 and fast_burn < 1.0
                 and (config["duty_low"] <= 0
                      or duty < config["duty_low"]))
        if not quiet:
            return
        floor = max(config["min_replicas"], 1)
        cooldown_ok = (
            now - state.last_down >= config["down_cooldown_s"]
            and now - state.last_up >= config["down_cooldown_s"])
        if replica_set is not None and actual > floor and cooldown_ok:
            state.desired = actual - 1
            state.last_down = now
            if replica_set.scale_down():
                self._decide(state, name, "down", "quiet",
                             {"from": actual, "to": actual - 1})
            return
        if (config["min_replicas"] == 0 and config["idle_s"] > 0
                and state.idle_since is not None
                and now - state.idle_since >= config["idle_s"]
                and cooldown_ok):
            self._scale_to_zero(name, state, config)

    def _predicted_recovery_s(self, snap: Optional[dict],
                              pending: int) -> float:
        """Queued work over the healthy fleet's service rate: the
        honest Retry-After a shed response carries."""
        if not snap:
            return MIN_RETRY_AFTER_S
        replicas = snap.get("replicas") or []
        latencies = [r["ewma_latency_ms"] / 1000.0
                     for r in replicas if r["ewma_latency_ms"] > 0]
        mean_latency = (sum(latencies) / len(latencies)) \
            if latencies else 0.05
        healthy = max(snap.get("healthy", 1), 1)
        predicted = (pending + 1) * mean_latency / healthy
        return min(max(predicted, MIN_RETRY_AFTER_S),
                   MAX_RETRY_AFTER_S)

    # -- scale to zero / cold start ----------------------------------------

    def _scale_to_zero(self, name: str, state: _ModelState,
                       config: dict) -> None:
        core = self._core
        state.desired = 0
        state.last_down = time.monotonic()
        started = time.monotonic()
        # Pageable models go cold the cheap way: weights move to host
        # through the hbm allocator (ledger rows park in the
        # paged_out side table, the instance stays registered) and
        # the warm estimate is bytes over measured restore bandwidth.
        # Everything else keeps the PR-17 full unload/reload cycle.
        mode = "paged"
        try:
            info = core.page_out_model(name)
        except Exception:  # noqa: BLE001
            _LOG.exception("scale-to-zero page-out of '%s' failed",
                           name)
            info = None
        if info is not None:
            estimate = max(info["restore_estimate_s"],
                           MIN_RETRY_AFTER_S)
        else:
            mode = "unloaded"
            try:
                core.unload_model(name)
            except Exception:  # noqa: BLE001
                _LOG.exception("scale-to-zero unload of '%s' failed",
                               name)
                return
            # The drain time is a decent first warm-time estimate
            # (load and unload both walk the executable); measured
            # reload time replaces it after the first cold start.
            estimate = max(time.monotonic() - started,
                           DEFAULT_WARM_ESTIMATE_S)
        with self._lock:
            self._cold[name] = _ColdModel(estimate, mode=mode)
        self._decide(state, name, "down", "scale_to_zero",
                     {"idle_s": round(config["idle_s"], 3),
                      "warm_estimate_s": round(estimate, 3),
                      "mode": mode})

    def on_admission_miss(self, name: str) -> Optional[float]:
        """Cold-start hook: ``core.infer`` calls this when acquire
        fails for a model. For a model THIS controller scaled to zero
        it kicks exactly one background reload and returns the honest
        Retry-After (remaining warm time) the 503 should carry; for
        anything else it returns None and the original error stands."""
        with self._lock:
            cold = self._cold.get(name)
            if cold is None:
                return None
            now = time.monotonic()
            if not cold.loading:
                cold.loading = True
                cold.load_started = now
                thread = threading.Thread(
                    target=self._cold_start, args=(name,),
                    name="autoscale-coldstart-%s" % name, daemon=True)
                thread.start()
            remaining = cold.warm_estimate_s - (now - cold.load_started)
        return max(remaining, MIN_RETRY_AFTER_S)

    def _cold_start(self, name: str) -> None:
        core = self._core
        with self._lock:
            cold = self._cold.get(name)
            mode = cold.mode if cold is not None else "unloaded"
        started = time.monotonic()
        try:
            # A paged model warms by restoring its weights
            # (chunked-parallel host->device through the hbm
            # allocator); restore_model returns False when the lease
            # is gone (e.g. an unload raced us), and the factory
            # reload covers that. core.load_model itself restores
            # when paged, so the fallthrough is safe either way.
            if mode != "paged" or not core.restore_model(name):
                core.load_model(name)
        except Exception:  # noqa: BLE001 — includes the allocator's
            # honest deferral when the restore loses the per-device
            # arbitration: the 503 already told the client when to
            # retry, and re-arming lets the next arrival try again.
            _LOG.exception("cold start of '%s' failed", name)
            with self._lock:
                cold = self._cold.get(name)
                if cold is not None:
                    # Re-arm: the next arrival may retry the load
                    # (a transient factory failure must not strand
                    # the model cold forever).
                    cold.loading = False
            return
        warm_s = time.monotonic() - started
        with self._lock:
            self._cold.pop(name, None)
            state = self._states.get(name)
        if state is not None:
            state.desired = 1
            self._decide(state, name, "up", "cold_start",
                         {"warm_s": round(warm_s, 3), "mode": mode})

    # -- audit + exposition ------------------------------------------------

    def _decide(self, state: _ModelState, name: str, direction: str,
                reason: str, attrs: dict) -> None:
        """One decision = one flight ring record + one incident stamp
        + one event counter bump + the /v2/debug last-decision row."""
        state.last_decision = direction
        state.last_reason = reason
        state.last_decision_ts = time.time()
        key = (direction, reason)
        with self._lock:
            state.events[key] = state.events.get(key, 0) + 1
        label = "autoscale_%s reason=%s" % (direction, reason)
        core = self._core
        try:
            core.flight.record_decision(name, label, attrs)
            core.flight.mark_incident(name, label)
        except Exception:  # noqa: BLE001 — audit is advisory
            pass
        _LOG.info("autoscale decision model=%s direction=%s reason=%s "
                  "%s", name, direction, reason, attrs)

    def snapshot(self) -> Dict[str, dict]:
        """Per-model controller state for /v2/debug's ``controller``
        section and the tpu_replica_desired / tpu_scale_events_total /
        tpu_replica_seconds_total families."""
        core = self._core
        out: Dict[str, dict] = {}
        with self._lock:
            states = dict(self._states)
            cold = {name: c.mode for name, c in self._cold.items()}
        for name, state in states.items():
            with core._replica_lock:
                replica_set = core._replica_sets.get(name)
            actual = replica_set.count if replica_set else 0
            out[name] = {
                "desired": state.desired,
                "actual": actual,
                "last_decision": state.last_decision,
                "last_reason": state.last_reason,
                "last_decision_ts": state.last_decision_ts,
                "replica_seconds": round(state.replica_seconds, 3),
                "events": {"%s|%s" % k: v
                           for k, v in state.events.items()},
                "shed": {
                    "active": state.shed.active,
                    "retry_after_s": state.shed.retry_after_s,
                    "reason": state.shed.reason,
                },
                "cold": name in cold,
                "cold_mode": cold.get(name),
            }
        return out
